"""Continuous-batching LLM engine — the KServe/Triton-GPU serving runtime
replaced by a TPU-native design (SURVEY.md §2.6, BASELINE config #5: the
Llama InferenceService TTFT metric runs through this engine).

Split into the two halves the hardware wants:

  - **Scheduling** (C++ core, serving/scheduler.py): request queue, decode
    slots, prefill-bucket choice. Decisions only — never touches tensors.
  - **Execution** (this module): a fixed menu of compiled XLA programs —
    one prefill program per bucket length plus ONE decode program over all
    slots — so serving never recompiles. Static shapes are the TPU
    constraint the whole design bends around: variable prompts are padded
    up to a bucket; the decode batch always runs full-width with inactive
    slots masked by the engine.

Prefill priority keeps TTFT low; decode always re-batches every step
(continuous batching), so finished slots refill immediately from the queue.
"""

from __future__ import annotations

import collections
import functools
import math
import threading
import time
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.kvcache import RadixKVCache
from kubeflow_tpu.models import llama
from kubeflow_tpu.obs import metrics as obs_metrics
from kubeflow_tpu.obs.trace import TRACER, StepAggregator
from kubeflow_tpu.serving.scheduler import (DecodeAction, PrefillAction,
                                            PromptTooLong, make_scheduler)


def _ngram_draft(hist, lengths, k: int, n: int):
    """Prompt-lookup drafting, vectorized over slots (device-side — no host
    round-trip, so it can live inside the scanned decode program).

    hist: [B, L] token history — positions 0..lengths[b] are real (the token
    at `lengths` is the pending last token, recorded by the caller just
    before drafting); beyond that is stale garbage the masks exclude.
    For each slot: find the LATEST position j < lengths where the n-gram
    hist[j-n+1..j] equals the context's trailing n-gram hist[lengths-n+1..
    lengths], and propose the k tokens that followed it. Returns
    (drafts [B, k] int32, count [B] int32) — count is how many proposals
    are real (0 when no match / not enough known continuation tokens).
    """
    b, l = hist.shape
    gram_pos = jnp.clip(lengths[:, None] + jnp.arange(1 - n, 1)[None],
                        0, l - 1)
    gram = jnp.take_along_axis(hist, gram_pos, axis=1)  # [B, n]
    # window ending at j matches iff hist[j-n+1+t] == gram[t] for all t;
    # n static slices — the whole match is a handful of [B, L] compares
    m = jnp.ones((b, l - n + 1), bool)
    for t in range(n):
        m = m & (hist[:, t:l - n + 1 + t] == gram[:, t:t + 1])
    jend = jnp.arange(n - 1, l)[None]  # window-end position per column
    valid = m & (jend < lengths[:, None]) & (lengths[:, None] >= n)
    j_best = jnp.max(jnp.where(valid, jend, -1), axis=1)  # [B]; -1 = none
    dpos = jnp.clip(j_best[:, None] + 1 + jnp.arange(k)[None], 0, l - 1)
    drafts = jnp.take_along_axis(hist, dpos, axis=1).astype(jnp.int32)
    # continuation tokens are only known through position `lengths`
    count = jnp.where(j_best >= 0, jnp.clip(lengths - j_best, 0, k), 0)
    return drafts, count.astype(jnp.int32)


def _fold_seed24(seed: int) -> int:
    """Fold an arbitrary non-negative seed onto the f32-exact 24-bit range
    the packed sampling row can carry, via the splitmix64 finalizer.
    Collisions necessarily exist (2^24 buckets), but — unlike the previous
    plain modulus — seeds differing only in high bits, or by a fixed
    stride, do not trivially alias. Pure integer ops: deterministic across
    restarts, platforms, and Python versions."""
    mask = (1 << 64) - 1
    z = (seed + 0x9E3779B97F4A7C15) & mask
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & mask
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & mask
    return (z ^ (z >> 31)) & 0xFFFFFF


#: EMA step for the speculative-decode acceptance estimators (per-slot
#: draft-length policy AND the engine-wide tokens-per-round estimate the
#: chunk sizing / drain heuristic consume). ~0.25 re-anchors in a handful
#: of rounds after a workload shift while still smoothing round noise.
SPEC_EMA_ALPHA = 0.25


class AdaptiveDraftLen:
    """Per-slot EMA of accepted drafts per verify round → the NEXT round's
    draft length k (host-side policy; the device programs stay static by
    compiling one verify program per k in a small menu).

    Why adapt: a verify forward carries k+1 query positions, so its FLOPs
    and KV/history scatter cost grow with k while only ACCEPTED drafts pay
    back — static k keeps paying verify cost for drafts that never land
    once the text gets hard. The EMA tracks live acceptance per slot; each
    round drafts the smallest menu k covering the most optimistic DRAFTING
    slot (plus headroom). A round that accepts all k drafts observes k+1
    (the round was truncated by k, not by the model), so the estimate can
    climb back to k_max after a low-acceptance phase instead of ratcheting
    down permanently. Slots whose requests sample or carry penalties draft
    nothing; a batch with no drafting slot verifies at the smallest k —
    near plain-decode cost instead of k_max dead verify positions."""

    def __init__(self, k_max: int, n_slots: int, *,
                 alpha: float = SPEC_EMA_ALPHA, headroom: float = 1.25):
        if k_max < 1:
            raise ValueError("k_max must be >= 1")
        self.k_max = k_max
        self.alpha = alpha
        self.headroom = headroom
        menu, k = [], 1
        while k < k_max:     # powers of two, then k_max itself: the same
            menu.append(k)   # small-menu shape the chunk sizes use
            k *= 2
        menu.append(k_max)
        self.menu: list[int] = menu
        # optimistic start (and per-slot reset): one round of observations
        # re-anchors; the worst case of optimism is one round's surplus
        # verify positions, never junk tokens
        self.ema = np.full(n_slots, float(k_max))

    def observe(self, slot: int, accepted: int, k_round: int) -> None:
        """One verify round's outcome for `slot`: `accepted` drafts landed
        out of the `k_round` proposed. Saturated rounds (all drafts
        accepted) observe accepted+1 — the truncation was k, not the
        model — capped at k_max so the estimate can never exceed the
        configured maximum."""
        obs = min(self.k_max,
                  accepted + (1 if accepted >= k_round else 0))
        self.ema[slot] += self.alpha * (obs - self.ema[slot])

    def reset_slot(self, slot: int) -> None:
        """A new request entered the slot: its text is unknown — back to
        optimistic."""
        self.ema[slot] = float(self.k_max)

    def pick(self, drafting_slots) -> int:
        """Draft length for a round whose drafting-eligible slots are
        `drafting_slots` (greedy, penalty-free). The most optimistic slot
        sets k (acceptance is per-slot, cost is batch-wide but small next
        to the weight read); no drafting slot → smallest k."""
        slots = list(drafting_slots)
        if not slots:
            return self.menu[0]
        want = max(self.ema[s] for s in slots) * self.headroom
        for k in self.menu:
            if k >= want:
                return k
        return self.k_max


class LLMEngine:
    """Continuous-batching generation over llama-family params: greedy by
    default, per-request temperature/top-k/top-p sampling, stop sequences,
    logprobs, and chunk-boundary cancellation."""

    #: obs component label (overridden by role engines: prefill/decode/
    #: stage_sharded) — the `component=` of every engine-side metric and
    #: the role attribute of engine spans
    role = "engine"

    #: KV residency: "slab" = preallocated [n_slots, max_len] rows;
    #: serving/paged.py overrides to "paged" (block pool + tables)
    kv_layout = "slab"

    #: the prefix banker extracts raw slot KV (slab layout), so warmup
    #: pre-compiles the raw-extract menu; the paged engine banks block
    #: ids instead (zero-copy) and has no such menu to warm
    _bank_uses_raw_extract = True
    #: continuation programs re-write the prefix KV into the slot rows
    #: (slab layout); the paged engine's spliced table blocks already
    #: hold those bytes, so it skips the write
    _cont_writes_prefix = True

    def __init__(self, params, cfg: llama.LlamaConfig, *, n_slots: int = 4,
                 max_len: int = 512, buckets: Sequence[int] = (64, 128, 256),
                 max_queue: int = 1024, eos_id: int | None = None,
                 prefer_native: bool = True, decode_chunk: int = 8,
                 mesh=None, sample_seed: int = 0,
                 prefix_cache: bool = False, max_prefixes: int = 4,
                 prefix_cache_blocks: int | None = None,
                 quantize: str | None = None,
                 warm_cont_pairs: int | None = 4,
                 kv_quantize: str | None = None,
                 decode_attention_impl: str | None = None,
                 prefill_attention_impl: str | None = None,
                 speculative: int | None = None,
                 spec_ngram: int = 3,
                 spec_adaptive: bool = True,
                 adapters: dict[str, dict[str, Any]] | None = None,
                 logprobs_topk: int = 0,
                 sample_k_max: int = 64,
                 pipeline_decode: bool = True):
        if max(buckets) >= max_len:
            raise ValueError("largest bucket must leave room to decode")
        if quantize not in (None, "int8"):
            raise ValueError(f"unknown quantize mode {quantize!r}")
        if kv_quantize not in (None, "int8"):
            raise ValueError(f"unknown kv_quantize mode {kv_quantize!r}")
        if speculative is not None and not 1 <= speculative <= 16:
            raise ValueError("speculative must be 1..16 draft tokens")
        if not 1 <= spec_ngram <= 8:
            # an upper bound too: a gram longer than the history window
            # would trace a zero-size reduction in _ngram_draft — fail
            # loudly at construction, not deep inside warmup
            raise ValueError("spec_ngram must be 1..8")
        if not 0 <= logprobs_topk <= 16:
            raise ValueError("logprobs_topk must be 0..16")
        if sample_k_max < 1:
            raise ValueError("sample_k_max must be >= 1")
        # -- sampling parity (⊘ kserve huggingfaceserver, SURVEY §2.4): the
        # decode/prefill/verify programs sample with per-request
        # temperature + top-k + top-p INSIDE the compiled programs (static
        # shapes: nucleus filtering runs over the top `sample_k_max`
        # candidates via lax.top_k — requests may not ask for a larger
        # top_k). Every program also emits the chosen token's raw-model
        # logprob; logprobs_topk > 0 additionally emits the top-N
        # alternatives per position (a static program-output width, so it
        # is an engine-level knob, not a per-request one).
        self.logprobs_topk = logprobs_topk
        self.sample_k_max = sample_k_max
        # -- speculative decoding (prompt-lookup/n-gram drafting, fully
        # device-resident): each "decode" dispatch becomes a scan of verify
        # steps — draft k tokens by matching the context's trailing n-gram
        # against a device-side token-history buffer, verify all k+1
        # positions in ONE forward (llama.verify_step), accept the longest
        # argmax-matching prefix. Greedy output is EXACTLY the
        # non-speculative output (tested); the win is tokens-per-dispatch
        # on copy-heavy / low-entropy text where drafts accept. Drafting,
        # verification, and acceptance all run inside the compiled program;
        # the host only fetches (count, tokens) rows — on a tunneled
        # device nothing else keeps the RTT amortized.
        self.spec = speculative
        self.spec_ngram = spec_ngram
        # programs keyed by (rounds, attention span, draft length k): the
        # adaptive-k policy dispatches smaller-k members of the same menu
        self._spec_fns: dict[tuple[int, int, int], Any] = {}
        self._spec_tokens = 0
        self._spec_verifies = 0
        # -- adaptive draft length (per-slot EMA acceptance): the verify
        # forward's cost grows with k but only accepted drafts pay back,
        # so each round drafts the smallest compiled k covering the live
        # acceptance estimate (AdaptiveDraftLen). Off (or k_max == 1) →
        # static k, the pre-r6 behavior.
        self.spec_adaptive = bool(spec_adaptive and speculative
                                  and speculative > 1)
        self._spec_adapt = (AdaptiveDraftLen(speculative, n_slots)
                            if self.spec_adaptive else None)
        self._spec_last_k = speculative or 0
        # EMA of delivered tokens per verify round (ADVICE r5 #2: the
        # lifetime average never decayed, so chunk sizing and the drain
        # heuristic tracked a long-dead workload after a shift)
        self._spec_round_ema: float | None = None
        # -- multi-adapter LoRA serving (S-LoRA-style, XLA-shaped): many
        # fine-tunes of ONE base share the continuous batch. adapters =
        # {name: {"lora": {target: {"a": [L,d,r], "b": [L,r,out]}},
        #         "alpha": float}} — stacked on device as [L, A+1, ...]
        # with index 0 the all-zero adapter (base-only rows), b pre-scaled
        # by alpha/rank so no per-adapter scalar rides the programs. Every
        # program gathers each row's (a, b) by the slot's adapter id; the
        # low-rank bypass is tiny next to the W reads decode is bound on.
        self.adapters = None
        self._adapter_idx: dict[str, int] = {}
        self._req_aids: dict[int, int] = {}
        self._raw_adapters = dict(adapters) if adapters else None
        if adapters:
            self._adapter_idx = {n: i + 1
                                 for i, n in enumerate(sorted(adapters))}
        # packed wave rows end with [slot, prompt_len, temp_milli, top_k,
        # top_p_micro, presence_milli, freq_milli, seed] and, under
        # multi-adapter serving, an adapter-id column
        self._row_extra = 9 if adapters else 8
        # -- decode-attention impl (ISSUE 15): "xla" einsum vs the fused
        # Pallas "flash" kernel over the KV slab (ops/flash_decode.py) —
        # a convenience override of cfg.decode_attention_impl, so bench
        # A/B pairs and runtime configs need not rebuild the LlamaConfig.
        # Static per engine: warmup compiles exactly the selected impl's
        # menu (an A/B bench builds TWO engines — the menu never carries
        # both impls for live traffic).
        if decode_attention_impl is not None:
            import dataclasses

            cfg = dataclasses.replace(
                cfg, decode_attention_impl=decode_attention_impl)
        if prefill_attention_impl is not None:
            # the prefill twin (ISSUE 20): same convenience override,
            # same static-per-engine pinning below
            import dataclasses

            cfg = dataclasses.replace(
                cfg, prefill_attention_impl=prefill_attention_impl)
        if mesh is not None and cfg.prefill_attention_impl == "auto":
            # same GSPMD boundary as decode below: no SPMD rule for the
            # pallas call — sharded-cache prefill programs keep the mha
            # einsum unless the operator explicitly claims "flash"
            import dataclasses

            cfg = dataclasses.replace(cfg, prefill_attention_impl="xla")
        if cfg.prefill_attention_impl == "auto":
            import dataclasses

            cfg = dataclasses.replace(
                cfg,
                prefill_attention_impl=llama.resolve_prefill_attn(cfg))
        if mesh is not None and cfg.decode_attention_impl == "auto":
            # GSPMD tensor-parallel serving: a pallas custom call has no
            # SPMD partitioning rule, so "auto" must not hand the
            # sharded-cache programs to the kernel (XLA would replicate
            # the cache it exists to stream). The einsum path keeps the
            # mesh layout; kernel+collective overlap for tp layouts is
            # ROADMAP #5's remaining half. An EXPLICIT "flash" is
            # honored — the operator owns the layout claim.
            import dataclasses

            cfg = dataclasses.replace(cfg, decode_attention_impl="xla")
        if cfg.decode_attention_impl == "auto":
            # PIN the resolved impl at construction: a program compiled
            # lazily after warmup (cold span/chunk combos) re-traces
            # verify_inner, and an env flip or active-mesh context at
            # THAT moment must not hand one engine a mixed-impl menu —
            # nor let metrics()/healthz report an impl the warmed
            # programs don't run.
            import dataclasses

            cfg = dataclasses.replace(
                cfg,
                decode_attention_impl=llama.resolve_decode_attn(cfg))
        # int8 KV cache: decode re-reads the whole (span of the) cache
        # every step, so int8 storage halves that HBM traffic vs bf16 and
        # halves cache residency (2x slots or context at 8B scale);
        # per-token-per-head scales, bf16 attention compute
        self.kv_quantize = kv_quantize
        if quantize == "int8":
            # weight-only int8 (models/llama.quantize_params): decode is
            # HBM-bound on weight reads, so int8 storage is the serving
            # throughput lever; done BEFORE sharding so the shards are int8
            params = llama.quantize_params(params)
        self.quantize = quantize
        self.params = params
        self.cfg = cfg
        self.n_slots = n_slots
        self.max_len = max_len
        self.buckets = tuple(sorted(buckets))
        self.eos_id = eos_id
        self.scheduler = make_scheduler(n_slots, self.buckets, max_queue,
                                        prefer_native=prefer_native)
        self.mesh = None
        if mesh is not None:
            self._shard_over(mesh)
        if self._raw_adapters:
            # after mesh setup so the stack lands replicated on the mesh
            self.adapters = self._stack_adapters(self._raw_adapters)
            del self._raw_adapters
        self.cache = self._alloc_cache()
        self.lengths = self._put(np.zeros((n_slots,), np.int32))
        self.last_tokens = self._put(np.zeros((n_slots,), np.int32))
        # per-slot sampling state [temperature, top_k, top_p,
        # presence_penalty, frequency_penalty, seed] (0/0/0/0/0/-1 =
        # greedy, filters + penalties off, engine-keyed sampling) + the
        # program-threaded PRNG key: both live on device like the rest of
        # the slot state. seed >= 0 switches that row's sampling keys to
        # request-seeded derivation (reproducible across restarts); it
        # rides the f32 samp row, so seeds are quantized to < 2^24 at
        # submit (f32-exact integers).
        self.samp = self._put(self._samp_reset())
        self.rng_key = (jax.random.key(sample_seed) if self.mesh is None
                        else jax.device_put(jax.random.key(sample_seed),
                                            self._repl))
        # per-request (temperature, top_k, top_p, presence, frequency,
        # seed) mirror for wave packing
        self._req_samp: dict[int, tuple] = {}
        # host-side stop-sequence suffix matching at chunk boundaries
        self._req_stop: dict[int, list[list[int]]] = {}
        self._host_lengths = np.zeros((n_slots,), np.int64)
        self.decode_chunk = max(1, decode_chunk)
        # the chunk menu warmup compiles (powers of two up to this);
        # set_decode_chunk clamps here post-warmup
        self._decode_chunk_warm = self.decode_chunk
        # -- decode pipelining: one dispatched-but-unfetched chunk may be
        # in flight; _inflight tracks its planned KV rows per slot so the
        # next chunk's headroom/span see through the lag
        self.pipeline_decode = pipeline_decode
        self._pending: tuple | None = None
        self._inflight = np.zeros((n_slots,), np.int64)
        # -- decode-step attribution counters (training/profiling.py's
        # serving_decode_breakdown reads these): wall time the HOST spends
        # dispatching decode programs vs fetching+replaying their outputs.
        # Two perf_counter() calls per chunk — noise next to a dispatch.
        self._perf = {"dispatch_s": 0.0, "fetch_replay_s": 0.0,
                      "decode_chunks": 0, "decode_steps": 0,
                      "active_uploads": 0}
        # device-resident copy of the decode active mask: the mask only
        # changes at prefill/finish boundaries, so re-uploading it every
        # chunk paid a host->device transfer (~an RTT on a tunneled
        # device) per chunk for identical bytes
        self._active_host: np.ndarray | None = None
        self._active_dev = None
        self._warmed = False
        self._max_new: dict[int, int] = {}
        self._finish_reasons: dict[int, str] = {}

        self._prompts: dict[int, list[int]] = {}
        # rid -> instant its prefill left the queue (the engine popped
        # its PrefillAction): the queue_wait/prefill/decode phase split
        # request_timing() reports (the bench's interference attribution)
        self._prefill_start_t: dict[int, float] = {}
        self._results: dict[int, list[int]] = {}
        self._logprobs: dict[int, list[float]] = {}
        self._toplogprobs: dict[int, list[dict[int, float]]] = {}
        self._submit_t: dict[int, float] = {}
        self._first_token_t: dict[int, float] = {}
        self._done: set[int] = set()
        # -- cancellation (SURVEY §2.6 Triton-class runtimes support
        # request cancellation; a CB engine without it leaks decode
        # capacity under dropped clients). cancel() only QUEUES the id —
        # the engine thread applies it at the next chunk boundary (top of
        # step()), so no lock covers a device dispatch.
        self._cancel_pending: list[int] = []
        self._deadlines: dict[int, float] = {}
        self._cancelled_count = 0
        self._ttft_window: collections.deque[float] = collections.deque(
            maxlen=1024)
        # -- multi-tenant accounting (loadgen subsystem, ROADMAP #4): a
        # request may carry a tenant name; the scheduler sees a stable
        # integer id (max-min fair queue pop + admission caps live THERE —
        # the engine only maps names and surfaces per-request timing).
        self._tenant_idx: dict[str, int] = {}
        self._req_tenant: dict[int, str | None] = {}
        # per-request finish wall time (with _submit_t/_first_token_t this
        # is the TTFT/TPOT record the loadgen runner reads via
        # request_timing() BEFORE release())
        self._finish_t: dict[int, float] = {}
        # -- observability (ISSUE 17): optional per-request trace ids and
        # the hot-loop step AGGREGATOR (per-dispatch counter bumps only —
        # the one decode span a request gets is emitted retrospectively
        # at finish from timestamps already kept; check_observability.py
        # lints that no span objects are minted on the step/_do_decode
        # paths). _decode_mark snapshots the aggregator at first token so
        # the finish span can report the request's decode-step window.
        self._req_trace: dict[int, str] = {}
        self._decode_agg = StepAggregator()
        self._decode_mark: dict[int, tuple[int, int]] = {}
        # queue-depth gauges are pull-model: refreshed from the scheduler
        # at scrape time (weakref-held, so a dropped engine unregisters
        # itself)
        obs_metrics.add_scrape_hook(self, LLMEngine._obs_publish)
        # Guards submit vs. the engine-loop thread: held across
        # scheduler.submit + request-dict population so scheduler.next()
        # (also taken under it) can never hand out a prefill whose request
        # dicts aren't populated yet.
        self._submit_lock = threading.Lock()
        self._prefill_fns: dict[tuple[int, int], Any] = {}
        self._decode_fns: dict[int, Any] = {}
        # -- prefix KV reuse (the kvcache tentpole, vLLM/SGLang-style and
        # TPU-shaped): a radix/block-trie index (kvcache.RadixKVCache)
        # over token sequences maps to ref-counted device KV blocks of
        # `prefix_block_tokens` tokens each (gcd of the buckets, so every
        # bucket is a whole number of blocks). On admission the engine
        # takes the LONGEST cached block-aligned prefix, skips its
        # prefill compute, and runs a continuation program over the tail
        # only; after any prefill the prompt's aligned prefix is banked
        # block-by-block (deduplicated — a multi-turn session stores only
        # each turn's new suffix blocks). Blocks stay quantized when the
        # cache is int8 (half the residency); LRU eviction never reclaims
        # a block pinned by an in-flight admission.
        self.prefix_cache_enabled = prefix_cache
        self.max_prefixes = max_prefixes
        # COLD-START COST: the continuation-program menu is (block-
        # multiple prefix) × (tail bucket) × log2(n_slots) full-model
        # programs. warmup() pre-compiles only the first `warm_cont_pairs`
        # (prefix, tail) pairs (None = all); colder pairs compile lazily
        # on their first hit (that one wave pays ~seconds of XLA compile,
        # subsequent hits are warm).
        self.warm_cont_pairs = warm_cont_pairs
        self.prefix_block_tokens = 0
        self.kvcache: RadixKVCache | None = None
        if prefix_cache:
            bt = math.gcd(*self.buckets)
            self.prefix_block_tokens = bt
            if prefix_cache_blocks is None:
                # legacy sizing: max_prefixes was "whole largest-bucket
                # prefixes"; the block pool holds the same token volume
                prefix_cache_blocks = max(1, max_prefixes) \
                    * (self.buckets[-1] // bt)
            self.kvcache = RadixKVCache(bt, prefix_cache_blocks)
        self._prefix_hits = 0
        self._prefix_misses = 0
        # rid -> reused prefix length, set at prefill dispatch (the
        # cached_tokens / request_timing surface); rid -> prompt length
        # survives the prompt pop at finish for the same surface
        self._cached_prefix: dict[int, int] = {}
        self._req_plen: dict[int, int] = {}
        # prefill-compute accounting (tracked with or without the cache:
        # the cold bench run needs the denominator too)
        self._prefill_computed_tokens = 0
        self._prefill_reused_tokens = 0
        self._cont_fns: dict[tuple[int, int], Any] = {}
        self._extract_fns: dict[int, Any] = {}
        self._extract_raw_fns: dict[int, Any] = {}

    def _samp_reset(self) -> np.ndarray:
        """Idle per-slot sampling state: all-zero except the seed column's
        -1 sentinel (unseeded)."""
        s = np.zeros((self.n_slots, 6), np.float32)
        s[:, 5] = -1.0
        return s

    def _shard_over(self, mesh) -> None:
        """Tensor-parallel serving (BASELINE #5 at 8B scale: one engine
        spanning a slice). Params shard by the model's logical axes
        (heads/mlp/vocab over `tensor`), the KV cache by kv-heads; GSPMD
        propagates the layout through the compiled prefill/decode programs
        and inserts the ICI collectives — the serving twin of the
        trainer's sharding path (training/trainer.py)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from kubeflow_tpu.parallel import MeshConfig
        from kubeflow_tpu.parallel.mesh import make_mesh
        from kubeflow_tpu.parallel.sharding import (shard_tree,
                                                    tree_logical_to_sharding)

        if isinstance(mesh, MeshConfig):
            mesh = make_mesh(mesh)
        tp = mesh.shape.get("tensor", 1)
        if self.cfg.n_kv_heads % max(tp, 1):
            raise ValueError(
                f"n_kv_heads={self.cfg.n_kv_heads} must divide by the "
                f"tensor axis ({tp}) to shard the KV cache")
        self.mesh = mesh
        self.params = shard_tree(
            self.params,
            tree_logical_to_sharding(
                llama.logical_axes_for(self.params, self.cfg), mesh))
        # no trailing None: GSPMD emits the trimmed spec on program outputs
        # and the jit cache compares specs structurally — a 5-element spec
        # here would retrace every program on its first post-warmup call
        self._cache_sh = NamedSharding(mesh, P(None, None, None, "tensor"))
        self._repl = NamedSharding(mesh, P())
        # penalty counts shard over the vocab axis like the lm_head logits
        # they edit; every program pins this layout (a free-floating GSPMD
        # choice on the output would retrace the menu after warmup)
        self._cnt_sh = NamedSharding(mesh, P(None, "tensor"))

    def _alloc_cache(self):
        """KV cache in its final layout. Under a mesh each device allocates
        only ITS shard (make_array_from_callback) — an 8B-scale cache that
        only fits sharded must never be materialized whole on one device."""
        if self.mesh is None:
            cache = llama.init_cache(self.cfg, self.n_slots, self.max_len,
                                     kv_quantize=self.kv_quantize)
            # per-slot generated-token counts (int32 over the vocab) back
            # the presence/frequency penalties: ~0.5 MB/slot at 8B vocab,
            # read once per sampled row — noise next to the weight read
            cache["cnt"] = jnp.zeros((self.n_slots, self.cfg.vocab_size),
                                     jnp.int32)
            if self.spec:
                cache["hist"] = jnp.zeros((self.n_slots, self.max_len),
                                          jnp.int32)
            if self.adapters is not None:
                cache["aids"] = jnp.zeros((self.n_slots,), jnp.int32)
            return cache
        # schema derives from init_cache — ONE source of truth for the
        # cache layout (shared with serving/contract.py)
        leaves = jax.eval_shape(lambda: llama.init_cache(
            self.cfg, self.n_slots, self.max_len,
            kv_quantize=self.kv_quantize))

        def zeros_shard(sds):
            def cb(index):
                shard = tuple(len(range(*sl.indices(dim)))
                              for sl, dim in zip(index, sds.shape))
                return np.zeros(shard, sds.dtype)
            return cb

        # the 4-element spec shards dim 3 (kv heads) for both the 5D int8
        # payloads and the 4D scale planes
        cache = {
            name: jax.make_array_from_callback(sds.shape, self._cache_sh,
                                               zeros_shard(sds))
            for name, sds in leaves.items()}
        cache["cnt"] = jax.device_put(
            np.zeros((self.n_slots, self.cfg.vocab_size), np.int32),
            self._cnt_sh)
        if self.spec:
            # the token-history buffer is tiny: replicate it
            cache["hist"] = jax.device_put(
                np.zeros((self.n_slots, self.max_len), np.int32), self._repl)
        if self.adapters is not None:
            cache["aids"] = jax.device_put(
                np.zeros((self.n_slots,), np.int32), self._repl)
        return cache

    def _put(self, x):
        """Host array → device; replicated across the mesh when sharded
        (uncommitted single-device inputs would fight GSPMD's layouts)."""
        if self.mesh is None:
            return jnp.asarray(x)
        return jax.device_put(jnp.asarray(x), self._repl)

    def _stack_adapters(self, adapters: dict[str, dict]):
        """{name: {"lora": {t: {"a","b"}}, "alpha": f}} → device stacks
        {t: {"a": [L, A+1, d_in, r], "b": [L, A+1, r, d_out]}}. Index 0 is
        the all-zero adapter (base-only rows); b carries alpha/rank so the
        programs need no per-adapter scalar. All adapters must agree on
        rank and targets (they share one compiled gather shape)."""
        names = sorted(adapters)
        first = adapters[names[0]]["lora"]
        targets = sorted(first)
        bad = set(targets) - set(llama.QUANT_LEAVES)
        if bad:
            # mirror LoraLlamaConfig.__post_init__: a typo'd target (e.g.
            # 'Wq') through the direct engine API must fail loudly here —
            # _adapted would otherwise silently serve the base weights
            raise ValueError(f"unknown adapter targets {sorted(bad)}; "
                             f"known: {sorted(llama.QUANT_LEAVES)}")
        rank = first[targets[0]]["a"].shape[-1]
        stack = {}
        for t in targets:
            a_rows, b_rows = [], []
            for n in names:
                tree = adapters[n]["lora"]
                if sorted(tree) != targets:
                    raise ValueError(
                        f"adapter {n!r} targets {sorted(tree)} != {targets}")
                a, b = tree[t]["a"], tree[t]["b"]
                if a.shape[-1] != rank:
                    raise ValueError(
                        f"adapter {n!r} rank {a.shape[-1]} != {rank}; "
                        "all adapters in one engine share a rank")
                scale = float(adapters[n].get("alpha", rank)) / rank
                a_rows.append(np.asarray(a, np.float32))
                b_rows.append(np.asarray(b, np.float32) * scale)
            a0 = np.zeros_like(a_rows[0])
            b0 = np.zeros_like(b_rows[0])
            # [L, A+1, ...]: layer-leading for the lax.scan over layers
            stack[t] = {
                "a": self._put(np.stack([a0] + a_rows, axis=1)),
                "b": self._put(np.stack([b0] + b_rows, axis=1)),
            }
        return stack

    # -- compiled programs ---------------------------------------------------
    # params are an explicit argument, never a closure: a closed-over pytree
    # would be inlined into the HLO as constants (hundreds of MB shipped to
    # the compiler and frozen into the executable). All slot state (cache,
    # lengths, last_tokens) lives on device and is updated inside the jitted
    # programs — the host loop does exactly ONE device->host fetch per
    # iteration (the new tokens), which is what keeps per-step latency at
    # dispatch cost instead of several tunnel round-trips.

    def _choose(self, logits, samp, key, slots, counts, positions):
        """ONE sampler for every program. logits [R, V] f32 raw model
        logits; samp [R, 6] = (temperature, top_k, top_p, presence,
        frequency, seed) per row; slots [R] per-row slot ids — unseeded
        sampling keys derive from the SLOT id, so padded duplicate rows
        (same slot, same data) sample identically and duplicate writes
        stay idempotent; counts [R, V] int32 per-row generated-token
        counts (the penalty state); positions [R] the generation position
        being sampled (prompt_len + #generated — the seeded-key input).
        Returns (next_key, tokens).

        Per-row semantics (mixing freely within one continuous batch):
          temp == 0              → greedy (bit-exact argmax over the
                                   penalized logits; with penalties off
                                   `x - 0.0` is bitwise x, so the
                                   greedy-exactness contract holds)
          temp > 0, no filters   → categorical over the full vocab
          top_k > 0 / top_p < 1  → nucleus/top-k over the top
                                   `sample_k_max` candidates (lax.top_k —
                                   the static-shape TPU form; submit()
                                   rejects top_k > sample_k_max, and a
                                   top_p nucleus wider than sample_k_max
                                   candidates is truncated there).
                                   Exact probability ties AT the cutoff
                                   admit every tied token (threshold-mass
                                   comparison), so a tie can widen the
                                   nucleus beyond the requested top_k /
                                   top_p — acceptable for f32 real-model
                                   logits where exact ties are rare.
          presence/frequency ≠ 0 → OpenAI penalties as logit edits over
                                   GENERATED tokens only (the vLLM
                                   convention): logits - presence·1[cnt>0]
                                   - frequency·cnt, applied before
                                   temperature/filters; greedy rows argmax
                                   the penalized logits (OpenAI applies
                                   penalties at temperature 0 too)
          seed >= 0              → that row's key derives from
                                   (seed, position) alone — deterministic
                                   across restarts, slots, and chunking
        top_p uses the standard smallest-prefix rule: keep candidate j
        while the cumulative mass BEFORE j is < p (so the first candidate
        always survives)."""
        temps, topks, topps = samp[:, 0], samp[:, 1], samp[:, 2]
        pres, freq = samp[:, 3], samp[:, 4]
        seeds = samp[:, 5].astype(jnp.int32)
        key, sub = jax.random.split(key)
        unseeded = jax.vmap(lambda s: jax.random.fold_in(sub, s))(slots)
        seeded = jax.vmap(
            lambda sd, pos: jax.random.fold_in(
                jax.random.fold_in(jax.random.key(sd), pos), 0x5eed))(
            jnp.maximum(seeds, 0), positions.astype(jnp.int32))
        row_keys = jax.random.wrap_key_data(jnp.where(
            (seeds >= 0)[:, None], jax.random.key_data(seeded),
            jax.random.key_data(unseeded)))
        # penalties: pres/freq == 0 rows subtract exactly 0.0, keeping
        # greedy argmax bit-identical to the raw logits. The whole edit —
        # two [R, V] f32 conversions of the count buffer plus the
        # multiply-subtracts — rides a lax.cond on "any row penalized":
        # the common all-unpenalized batch skips reading the counts at
        # all (identity branch returns logits bitwise unchanged, so the
        # greedy-exactness contract is preserved either way).
        def penalize(lg):
            return (lg
                    - pres[:, None] * (counts > 0).astype(jnp.float32)
                    - freq[:, None] * counts.astype(jnp.float32))

        logits = jax.lax.cond(jnp.any((pres != 0) | (freq != 0)),
                              penalize, lambda lg: lg, logits)
        greedy = jnp.argmax(logits, -1).astype(jnp.int32)

        # The whole sampling pipeline (softmax + top_k window +
        # categorical over the vocab) is gated behind lax.cond on "any
        # row sampling": an all-greedy batch — the common serving case —
        # skips it entirely, which at 8B vocab is a measurable slice of
        # every decode step. The key chain advances BEFORE the cond
        # (split above), so seeded determinism is branch-independent.
        def sample_branch(logits):
            scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
            # ONE categorical serves both modes: the filters reduce to a
            # per-row probability THRESHOLD (the smallest admitted
            # candidate's mass, from the sorted top-sample_k_max
            # prefix), and rows with filters off get threshold 0 — the
            # mask is then all-pass and the draw is BIT-IDENTICAL to an
            # unfiltered categorical, so the "top_p=1/top_k=0 matches
            # plain sampling" contract holds by construction, not by a
            # second code path.
            kmax = min(self.sample_k_max, logits.shape[-1])
            probs = jax.nn.softmax(scaled, axis=-1)
            top_vals, _ = jax.lax.top_k(probs, kmax)     # sorted desc
            cum = jnp.cumsum(top_vals, axis=-1)
            # admit candidate j while the mass BEFORE j is < p (p off =>
            # 2.0 admits all) and j < top_k (off => kmax)
            keep_p = (cum - top_vals) < jnp.where(
                (topps > 0) & (topps < 1), topps, 2.0)[:, None]
            kk = jnp.where(topks > 0, jnp.minimum(topks, kmax), kmax)
            keep = keep_p & (jnp.arange(kmax)[None] < kk[:, None])
            n_keep = jnp.maximum(jnp.sum(keep, axis=-1), 1)
            thr = jnp.take_along_axis(top_vals, n_keep[:, None] - 1,
                                      axis=1)[:, 0]
            use_filter = (topks > 0) | ((topps > 0) & (topps < 1))
            thr = jnp.where(use_filter, thr, 0.0)
            masked = jnp.where(probs >= thr[:, None], scaled, -jnp.inf)
            sampled = jax.vmap(
                lambda rk, row: jax.random.categorical(rk, row))(
                row_keys, masked).astype(jnp.int32)
            return jnp.where(temps > 0, sampled, greedy)

        toks = jax.lax.cond(jnp.any(temps > 0), sample_branch,
                            lambda _: greedy, logits)
        return key, toks

    def _pack_out(self, toks, logits):
        """Program output row per sampled token: [tok, logprob(, top-N ids,
        top-N logprobs)] as ONE f32 array — a single packed fetch keeps the
        host loop at one RTT per iteration (token ids are exact in f32 for
        any vocab < 2^24). Logprobs are of the RAW model distribution
        (temperature-independent), the OpenAI convention."""
        lse = jax.nn.logsumexp(logits, axis=-1)
        lp = jnp.take_along_axis(logits, toks[..., None],
                                 axis=-1)[..., 0] - lse
        cols = [toks.astype(jnp.float32)[..., None], lp[..., None]]
        if self.logprobs_topk:
            tv, tid = jax.lax.top_k(logits, self.logprobs_topk)
            cols += [tid.astype(jnp.float32), tv - lse[..., None]]
        return jnp.concatenate(cols, axis=-1)

    @property
    def _out_cols(self) -> int:
        return 2 + 2 * self.logprobs_topk

    def _unpack_out(self, row):
        """Host twin of _pack_out: np row → (tok, lp, top|None) where top
        is a {token_id: logprob} dict of the top-N alternatives."""
        tok, lp = int(row[0]), float(row[1])
        if not self.logprobs_topk:
            return tok, lp, None
        n = self.logprobs_topk
        return tok, lp, {int(t): float(l)
                         for t, l in zip(row[2:2 + n], row[2 + n:2 + 2 * n])}

    def _unpack_wave(self, wave):
        """Row layout: tokens ++ [slot, prompt_len, temp_milli, top_k,
        top_p_micro, presence_milli, freq_milli, seed(, aid)]. Returns
        (tokens, slots, prompt_lens, row_samp [W, 6], aids|None)."""
        ex = self._row_extra
        tokens = wave[:, :-ex]
        slots, prompt_lens = wave[:, -ex], wave[:, -ex + 1]
        row_samp = jnp.stack([
            wave[:, -ex + 2].astype(jnp.float32) / 1000.0,
            wave[:, -ex + 3].astype(jnp.float32),
            wave[:, -ex + 4].astype(jnp.float32) / 1e6,
            wave[:, -ex + 5].astype(jnp.float32) / 1000.0,
            wave[:, -ex + 6].astype(jnp.float32) / 1000.0,
            wave[:, -ex + 7].astype(jnp.float32),
        ], axis=1)
        aids = wave[:, -1] if self.adapters is not None else None
        return tokens, slots, prompt_lens, row_samp, aids

    def _prefill(self, params, cache, lengths, last_tokens, samp, key,
                 wave, lora=None):
        """Batched prefill wave. `wave` is ONE packed int32 array
        [W, bucket+ex] — row i = prompt tokens (right-padded) ++ [slot,
        prompt_len, temp_milli, top_k, top_p_micro] (++ adapter id under
        multi-adapter serving) — because on a tunneled device every
        host->device transfer costs a full RTT: one packed transfer + one
        dispatch covers a whole burst of arrivals. Padded wave rows
        duplicate a real row (same slot, same data) and sampling keys
        derive from the slot id, so duplicate writes are idempotent even
        for sampled requests. Returns packed [W, out_cols] rows
        (_pack_out)."""
        tokens, slots, prompt_lens, row_samp, aids = self._unpack_wave(wave)
        logits, ks, vs = llama.prefill(params, tokens, self.cfg,
                                       lora=lora, ids=aids)
        bucket = tokens.shape[1]
        cache = dict(cache)
        lasts = []
        for i in range(tokens.shape[0]):   # W is static: unrolled updates
            cache = self._cache_write(cache, slots[i], 0, bucket,
                                      ks[:, i], vs[:, i])
            lengths = lengths.at[slots[i]].set(prompt_lens[i])
            samp = samp.at[slots[i]].set(row_samp[i])
            if aids is not None:
                cache["aids"] = cache["aids"].at[slots[i]].set(aids[i])
            lasts.append(jax.lax.dynamic_index_in_dim(
                logits[i], prompt_lens[i] - 1, keepdims=False))
        stacked = jnp.stack(lasts)
        # penalties count GENERATED tokens only: the first sampled token
        # sees zero counts, and the slot's counts reset to exactly its
        # one-hot (idempotent under padded duplicate rows, unlike .add)
        cnt = cache["cnt"]
        zero_cnt = jnp.zeros((tokens.shape[0], cnt.shape[1]), cnt.dtype)
        key, toks = self._choose(stacked, row_samp, key, slots, zero_cnt,
                                 prompt_lens)
        for i in range(tokens.shape[0]):
            last_tokens = last_tokens.at[slots[i]].set(toks[i])
            cnt = cnt.at[slots[i]].set(
                jax.nn.one_hot(toks[i], cnt.shape[1], dtype=cnt.dtype))
        cache["cnt"] = self._constrain_cnt(cnt)
        if self.spec:
            # token-history mirror of the KV writes (n-gram drafting reads
            # it); pad garbage past prompt_len is never read — the matcher
            # masks positions > lengths
            hist = cache["hist"]
            for i in range(tokens.shape[0]):
                hist = hist.at[slots[i], :bucket].set(tokens[i])
            cache["hist"] = hist
        return (cache, lengths, last_tokens, samp, key,
                self._pack_out(toks, stacked))

    def _cache_write(self, cache, slot, start: int, count: int, ks, vs):
        """Write [L, count, kv, hd] KV rows into a slot's [start, start+count)
        range, quantizing when the cache is int8. start/count are static."""
        out = dict(cache)
        if self.kv_quantize == "int8":
            kq, ksc = llama.quantize_kv(ks)
            vq, vsc = llama.quantize_kv(vs)
            out["k"] = cache["k"].at[:, slot, start:start + count].set(kq)
            out["v"] = cache["v"].at[:, slot, start:start + count].set(vq)
            out["k_s"] = cache["k_s"].at[:, slot,
                                         start:start + count].set(ksc)
            out["v_s"] = cache["v_s"].at[:, slot,
                                         start:start + count].set(vsc)
        else:
            out["k"] = cache["k"].at[:, slot, start:start + count].set(
                ks.astype(cache["k"].dtype))
            out["v"] = cache["v"].at[:, slot, start:start + count].set(
                vs.astype(cache["v"].dtype))
        return out

    def _prefill_cont(self, params, cache, lengths, last_tokens, samp, key,
                      wave, k_prefix, v_prefix, lora=None):
        """Batched continuation prefill against cached prefixes. `wave` is
        [W, T+ex] — tail tokens (prompt[P:], right-padded to the tail
        bucket) ++ [slot, full_prompt_len, temp_milli, top_k, top_p_micro
        (, aid)] per row; k/v_prefix: [L, W, P, kv, hd] (row i's prefix —
        different requests may hit DIFFERENT store entries of the same P).
        With speculative decoding on, rows are [tail(T) ++ prefix(P) ++
        extras] — the prefix KV alone can't populate the token-history
        buffer the n-gram drafter reads, so the prefix TOKENS ride the
        same packed transfer. Writes prefix+tail KV into each slot and
        samples next tokens from the tails' last rows; padded duplicate
        rows repeat their source row (idempotent writes), exactly like
        _prefill. Returns packed [W, out_cols] rows."""
        tokens_all, slots, prompt_lens, row_samp, aids = \
            self._unpack_wave(wave)
        p = k_prefix.shape[2]
        t_bucket = tokens_all.shape[1] - (p if self.spec else 0)
        tokens = tokens_all[:, :t_bucket]
        logits, ks, vs = llama.prefill_continue(params, tokens, k_prefix,
                                                v_prefix, self.cfg,
                                                lora=lora, ids=aids)
        cache = dict(cache)
        lasts = []
        for i in range(tokens.shape[0]):   # W is static: unrolled updates
            if self._cont_writes_prefix:
                cache = self._cache_write(cache, slots[i], 0, p,
                                          k_prefix[:, i], v_prefix[:, i])
            cache = self._cache_write(cache, slots[i], p, t_bucket,
                                      ks[:, i], vs[:, i])
            lengths = lengths.at[slots[i]].set(prompt_lens[i])
            samp = samp.at[slots[i]].set(row_samp[i])
            if aids is not None:
                cache["aids"] = cache["aids"].at[slots[i]].set(aids[i])
            lasts.append(jax.lax.dynamic_index_in_dim(
                logits[i], prompt_lens[i] - p - 1, keepdims=False))
        stacked = jnp.stack(lasts)
        cnt = cache["cnt"]
        zero_cnt = jnp.zeros((tokens.shape[0], cnt.shape[1]), cnt.dtype)
        key, toks = self._choose(stacked, row_samp, key, slots, zero_cnt,
                                 prompt_lens)
        for i in range(tokens.shape[0]):
            last_tokens = last_tokens.at[slots[i]].set(toks[i])
            cnt = cnt.at[slots[i]].set(
                jax.nn.one_hot(toks[i], cnt.shape[1], dtype=cnt.dtype))
        cache["cnt"] = self._constrain_cnt(cnt)
        if self.spec:
            hist = cache["hist"]
            prefix_toks = tokens_all[:, t_bucket:]
            for i in range(tokens.shape[0]):
                hist = hist.at[slots[i], :p].set(prefix_toks[i])
                hist = hist.at[slots[i], p:p + t_bucket].set(tokens[i])
            cache["hist"] = hist
        return (cache, lengths, last_tokens, samp, key,
                self._pack_out(toks, stacked))

    def _extract_prefix(self, cache, slot, *, p: int):
        """Slice a freshly prefilled slot's first `p` KV rows into a
        store-shaped [L, 1, P, kv, hd] entry (stays on device; entries are
        kept dequantized — the store is tiny next to the cache, and cont
        prefill re-quantizes on write)."""
        k = jax.lax.dynamic_index_in_dim(cache["k"], slot, axis=1,
                                         keepdims=False)[:, :p][:, None]
        v = jax.lax.dynamic_index_in_dim(cache["v"], slot, axis=1,
                                         keepdims=False)[:, :p][:, None]
        if self.kv_quantize == "int8":
            ksc = jax.lax.dynamic_index_in_dim(
                cache["k_s"], slot, axis=1, keepdims=False)[:, :p][:, None]
            vsc = jax.lax.dynamic_index_in_dim(
                cache["v_s"], slot, axis=1, keepdims=False)[:, :p][:, None]
            k = llama.dequantize_kv(k, ksc, self.cfg.dtype)
            v = llama.dequantize_kv(v, vsc, self.cfg.dtype)
        return k, v

    def _extract_prefix_raw(self, cache, slot, *, p: int):
        """Raw-layout twin of _extract_prefix for the radix block store:
        returns the slot's first `p` KV rows WITHOUT dequantizing —
        (k, v) in cache dtype, or (kq, k_scale, vq, v_scale) when the
        cache is int8 — so stored blocks keep the int8 residency win.
        All arrays are [L, 1, p, ...]; the block insert slices them
        along the token axis (axis 2)."""
        def take(name):
            return jax.lax.dynamic_index_in_dim(
                cache[name], slot, axis=1, keepdims=False)[:, :p][:, None]

        if self.kv_quantize == "int8":
            return take("k"), take("k_s"), take("v"), take("v_s")
        return take("k"), take("v")

    def _decode(self, params, cache, lengths, last_tokens, samp, key,
                active, lora=None, *, steps: int, span: int | None = None,
                sample: bool = True):
        """`steps` chained decode iterations inside ONE program (lax.scan):
        a K-token chunk costs one dispatch round-trip instead of K. Slots
        that finish (EOS) mid-chunk keep decoding on device; the host drops
        their surplus tokens, and the slot's next prefill resets its
        state. `span` statically bounds the attention window (length-aware
        decode — see llama.decode_step). Emits packed [steps, n_slots,
        out_cols] rows (_pack_out).

        `sample=False` is the PROFILER's variant (serving_decode_breakdown):
        raw argmax, no sampling pipeline, no penalty-count touch — timing
        it against the full program isolates the sampling+penalties bucket
        of a decode step. Never dispatched by live traffic."""
        slots = jnp.arange(self.n_slots)

        def body(carry, _):
            cache, lengths, last_tokens, key = carry
            aids = cache.get("aids")
            cnt = cache["cnt"]
            logits, kv = llama.decode_step(params, last_tokens, cache,
                                           lengths, self.cfg, span=span,
                                           lora=lora, ids=aids)
            if aids is not None:
                kv["aids"] = aids  # decode never re-assigns slots
            if sample:
                # seeded-key position: this step samples generated token
                # #(lengths - prompt_len + 2) at absolute position
                # lengths + 1 (prefill sampled token #1 AT position
                # prompt_len == lengths, so passing bare `lengths` would
                # reuse prefill's key)
                key, toks = self._choose(logits, samp, key, slots, cnt,
                                         lengths + 1)
                # the generated-token counts only feed the penalty logit
                # edits, and every prefill resets its slot's counts — so
                # an all-unpenalized batch skips the [slots, vocab]
                # scatter (read+write of the whole count buffer) entirely
                kv["cnt"] = self._constrain_cnt(jax.lax.cond(
                    jnp.any((samp[:, 3] != 0) | (samp[:, 4] != 0)),
                    lambda c: c.at[slots, toks].add(
                        active.astype(c.dtype)),
                    lambda c: c, cnt))
            else:
                toks = jnp.argmax(logits, -1).astype(jnp.int32)
                kv["cnt"] = cnt
            cache = kv
            lengths = lengths + active.astype(jnp.int32)
            last_tokens = jnp.where(active, toks, last_tokens)
            return ((cache, lengths, last_tokens, key),
                    self._pack_out(toks, logits))

        (cache, lengths, last_tokens, key), out = jax.lax.scan(
            body, (cache, lengths, last_tokens, key), None, length=steps)
        return cache, lengths, last_tokens, samp, key, out

    def _spec_decode(self, params, cache, lengths, last_tokens, samp, key,
                     active, lora=None, *, steps: int, span: int,
                     k_spec: int | None = None):
        """`steps` speculative verify rounds inside ONE program: each round
        records the pending token into the history buffer, drafts up to
        `k_spec` tokens by n-gram lookup (_ngram_draft), verifies all
        drafts in one llama.verify_step forward, and accepts the longest
        argmax-matching prefix plus the model's own bonus token — 1..k+1
        tokens per round per slot, at ~one decode-step's HBM cost. Greedy
        slots get EXACT greedy output (verification IS the greedy model);
        sampled slots (temp>0) draft nothing and sample the bonus (through
        the same top-k/top-p filters as plain decode), i.e. degrade to
        plain decode. Emits [steps, B, 1 + (k+1)*out_cols] f32 rows:
        count ++ flattened _pack_out rows per emit position.

        `k_spec` defaults to the engine's configured maximum; the
        adaptive-k policy dispatches smaller-k members of the menu when
        measured acceptance doesn't cover the configured draft count (any
        k is exact — fewer drafts only shortcut fewer dispatches)."""
        k_spec = self.spec if k_spec is None else k_spec
        rows = jnp.arange(self.n_slots)
        max_len = self.max_len
        temps = samp[:, 0]
        pens = (samp[:, 3] != 0) | (samp[:, 4] != 0)

        def body(carry, _):
            cache, lengths, last_tokens, key = carry
            hist = cache["hist"]
            # record the pending token at its cache position (inactive
            # slots' writes are dropped — their hist is dead state anyway,
            # but a clamped write at max_len-1 could land on a live row)
            hist = hist.at[rows, jnp.where(active, lengths, max_len)].set(
                last_tokens, mode="drop")
            drafts, count = _ngram_draft(hist, lengths, k_spec,
                                         self.spec_ngram)
            # sampled rows AND penalized rows draft nothing: penalties
            # evolve per emitted token, so parallel verification against
            # raw argmax would diverge from the sequential penalized
            # greedy — those rows degrade to plain (1-token) decode,
            # exactly like sampling does
            count = jnp.where(active & (temps <= 0) & ~pens, count, 0)
            tokens_in = jnp.concatenate([last_tokens[:, None], drafts],
                                        axis=1)
            aids = cache.get("aids")
            kv = {k: v for k, v in cache.items() if k != "hist"}
            logits, kv = llama.verify_step(params, tokens_in, kv, lengths,
                                           self.cfg, span=span, lora=lora,
                                           ids=aids)
            preds = jnp.argmax(logits, -1).astype(jnp.int32)  # [B, k+1]
            match = ((preds[:, :k_spec] == drafts)
                     & (jnp.arange(k_spec)[None] < count[:, None]))
            # length of the leading all-True run = accepted drafts
            n_acc = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1),
                            axis=1)
            bonus_greedy = jnp.take_along_axis(preds, n_acc[:, None],
                                               axis=1)[:, 0]
            cnt = cache["cnt"]
            # sampled rows accept no drafts, so the bonus is generated
            # token #(lengths+1 - prompt_len + 1) at absolute position
            # lengths + 1 — the same offset plain decode uses (bare
            # `lengths` would collide with the prefill-sampled key)
            key, bonus_chosen = self._choose(logits[:, 0], samp, key, rows,
                                             cnt, lengths + 1)
            # _choose returns penalized argmax for (temp=0, penalties-on)
            # rows and a filtered sample for temp>0 rows; pure-greedy rows
            # keep the verify forward's own prediction
            bonus = jnp.where((temps > 0) | pens, bonus_chosen,
                              bonus_greedy)
            jj = jnp.arange(k_spec + 1)[None]
            drafts_pad = jnp.concatenate(
                [drafts, jnp.zeros((self.n_slots, 1), jnp.int32)], axis=1)
            emit = jnp.where(jj < n_acc[:, None], drafts_pad,
                             jnp.where(jj == n_acc[:, None],
                                       bonus[:, None], 0))
            emit_count = jnp.where(active, n_acc + 1, 0).astype(jnp.int32)
            # emitted tokens enter the penalty counts (scatter-add; masked
            # positions add 0 at token id 0, duplicates accumulate) — but
            # only when some row actually carries a penalty: the counts
            # feed nothing else, and prefill resets them per slot, so the
            # all-unpenalized batch skips the [slots, vocab] scatter
            emit_mask = (jj < emit_count[:, None]).astype(cnt.dtype)
            kv["cnt"] = self._constrain_cnt(jax.lax.cond(
                jnp.any(pens),
                lambda c: c.at[rows[:, None], emit].add(emit_mask),
                lambda c: c, cnt))
            # accepted drafts enter the history now; the bonus token lands
            # next round as the pending last_token
            wpos = lengths[:, None] + 1 + jnp.arange(k_spec)[None]
            wmask = (jnp.arange(k_spec)[None] < n_acc[:, None]) \
                & active[:, None]
            hist = hist.at[rows[:, None],
                           jnp.where(wmask, wpos, max_len)].set(
                drafts, mode="drop")
            kv["hist"] = hist
            if aids is not None:
                kv["aids"] = aids
            new_len = lengths + emit_count
            new_last = jnp.where(active, bonus, last_tokens)
            # emitted token j's distribution is logits[:, j] (the verify
            # forward consumed tokens_in[:j+1] to produce it), so one
            # _pack_out over [B, k+1] yields every emit's logprob row
            out_rows = self._pack_out(emit, logits)  # [B, k+1, out_cols]
            packed = jnp.concatenate(
                [emit_count[:, None].astype(jnp.float32),
                 out_rows.reshape(self.n_slots, -1)], axis=1)
            return (kv, new_len, new_last, key), packed

        (cache, lengths, last_tokens, key), out = jax.lax.scan(
            body, (cache, lengths, last_tokens, key), None, length=steps)
        return cache, lengths, last_tokens, samp, key, out

    def _spec_fn(self, steps: int, span: int | None = None,
                 k: int | None = None):
        """Compiled speculative program per (rounds, attention span, draft
        length) — the spec-mode twin of _decode_fn's menu. k defaults to
        the engine's configured maximum (the static-k program)."""
        span = self.max_len if span is None else span
        k = self.spec if k is None else k
        if (steps, span, k) not in self._spec_fns:
            self._spec_fns[steps, span, k] = jax.jit(
                functools.partial(self._spec_decode, steps=steps, span=span,
                                  k_spec=k),
                donate_argnums=(1, 2, 3, 4, 5))
        return self._spec_fns[steps, span, k]

    def _prefill_fn(self, bucket: int, width: int):
        """One compiled program per (bucket, wave-width) pair; widths are
        powers of two so a burst of any size maps onto a tiny program menu."""
        if (bucket, width) not in self._prefill_fns:
            self._prefill_fns[bucket, width] = jax.jit(
                self._prefill, donate_argnums=(1, 2, 3, 4, 5))
        return self._prefill_fns[bucket, width]

    def _cont_fn(self, p: int, t: int, width: int):
        """One continuation program per (prefix bucket, tail bucket, wave
        width); the prefix KV args are NOT donated — store entries are
        reused (the stacked per-wave copy IS donatable, but stays alive
        only within the dispatch)."""
        if (p, t, width) not in self._cont_fns:
            self._cont_fns[p, t, width] = jax.jit(
                self._prefill_cont, donate_argnums=(1, 2, 3, 4, 5))
        return self._cont_fns[p, t, width]

    def _extract_fn(self, p: int):
        if p not in self._extract_fns:
            self._extract_fns[p] = jax.jit(
                functools.partial(self._extract_prefix, p=p))
        return self._extract_fns[p]

    def _extract_raw_fn(self, p: int):
        if p not in self._extract_raw_fns:
            self._extract_raw_fns[p] = jax.jit(
                functools.partial(self._extract_prefix_raw, p=p))
        return self._extract_raw_fns[p]

    def _tail_bucket(self, tail_len: int) -> int | None:
        cands = [b for b in self.buckets if b >= tail_len]
        return min(cands) if cands else None

    def _prefix_lookup(self, action):
        """(match, p, t) when the prompt's longest cached block chain
        yields a legal continuation dispatch (>= 1 tail token must
        remain to produce next-token logits, and the tail must fit a
        bucket inside max_len — shrinking the reused prefix block by
        block when the full match would overflow the cache). None on a
        miss. The returned match is PINNED: eviction cannot reclaim its
        blocks until the caller releases it after the dispatch."""
        prompt = self._prompts[action.req_id]
        bt = self.prefix_block_tokens
        if len(prompt) - 1 < bt:
            return None   # too short to carry even one block: not an
            # eligible admission, so neither a hit nor a miss
        tenant = self._req_tenant.get(action.req_id)
        m = self.kvcache.match(prompt, max_tokens=len(prompt) - 1,
                               namespace=self._req_aids.get(
                                   action.req_id, 0))
        p = m.tokens
        t = None
        while p > 0:
            t = self._tail_bucket(len(prompt) - p)
            if t is None:   # tail over the largest bucket: shrinking p
                p = 0       # only grows it — the chunked path owns this
                break
            if p + t <= self.max_len:
                break
            p -= bt
        if p <= 0:
            self.kvcache.release(m)
            self.kvcache.record_miss(tenant)
            self._prefix_misses += 1
            return None
        return m, p, t

    @staticmethod
    def _materialize_payloads(payloads: list, kv_quantize, dtype):
        """Block-payload chain → (k, v) prefix arrays [L, 1, P, kv, hd]
        in model dtype: concatenate along the token axis, dequantizing
        int8 blocks at the last moment (the store keeps them int8 — half
        the residency). Device-to-device only; nothing crosses the host.
        Static so the stage-sharded engine can run it per layer slab."""
        if kv_quantize == "int8":
            kq = jnp.concatenate([b[0] for b in payloads], axis=2)
            ks = jnp.concatenate([b[1] for b in payloads], axis=2)
            vq = jnp.concatenate([b[2] for b in payloads], axis=2)
            vs = jnp.concatenate([b[3] for b in payloads], axis=2)
            return (llama.dequantize_kv(kq, ks, dtype),
                    llama.dequantize_kv(vq, vs, dtype))
        if len(payloads) == 1:
            return payloads[0]
        return (jnp.concatenate([b[0] for b in payloads], axis=2),
                jnp.concatenate([b[1] for b in payloads], axis=2))

    def _materialize_prefix(self, payloads: list):
        """Matched block chain → the continuation program's (k, v)
        prefix arrays (see _materialize_payloads)."""
        return self._materialize_payloads(payloads, self.kv_quantize,
                                          self.cfg.dtype)

    def _stack_prefix(self, entries: list):
        """Stack per-request materialized prefixes into the continuation
        wave's (k_prefix, v_prefix) program inputs along the batch axis.
        entries: list of `_materialize_prefix` results, one per wave row.
        The stage-sharded engine overrides this to stack per layer slab."""
        return (jnp.concatenate([e[0] for e in entries], axis=1),
                jnp.concatenate([e[1] for e in entries], axis=1))

    @staticmethod
    def _payload_slice(parts, s: int, e: int):
        """One radix block's payload from the raw-extract arrays: the
        [s, e) token-axis slice of every part. The stage-sharded engine
        overrides this to slice each stage's parts (the block payload is
        then the per-stage tuple — the stage-keyed store's currency)."""
        return tuple(a[:, :, s:e] for a in parts)

    def _decode_fn(self, steps: int, span: int | None = None):
        """One compiled program per (chunk length, attention span) pair —
        chunk lengths are powers of two up to decode_chunk, spans powers of
        two from 128 to max_len (chosen by _do_decode from the live
        lengths). Cold pairs compile lazily on first use."""
        span = self.max_len if span is None else span
        if (steps, span) not in self._decode_fns:
            self._decode_fns[steps, span] = jax.jit(
                functools.partial(self._decode, steps=steps, span=span),
                donate_argnums=(1, 2, 3, 4, 5))
        return self._decode_fns[steps, span]

    def _decode_nosample_fn(self, steps: int, span: int | None = None):
        """The PROFILER's sampling-stripped decode variant (same call
        signature as _decode_fn's programs): raw argmax, no sampling
        pipeline, no penalty-count touch — timing it against the full
        program isolates the sampling bucket of the decode breakdown.
        A method (not an inline jit in the profiler) so the
        stage-sharded engine can supply its pipelined twin."""
        span = self.max_len if span is None else span
        return jax.jit(
            functools.partial(self._decode, steps=steps, span=span,
                              sample=False),
            donate_argnums=(1, 2, 3, 4, 5))

    def _span_menu(self) -> list[int]:
        """Attention-span buckets: powers of two from 128 up to (and always
        including) max_len."""
        spans = []
        s = 128
        while s < self.max_len:
            spans.append(s)
            s *= 2
        spans.append(self.max_len)
        return spans

    def _pick_span(self, needed: int) -> int:
        for s in self._span_menu():
            if s >= needed:
                return s
        return self.max_len

    # -- public API ----------------------------------------------------------

    def _chunk_plan(self, n: int) -> list[tuple[int, int]]:
        """Chunked-prefill schedule for an n-token prompt longer than the
        largest bucket: [(chunk_len, program_len), ...] — full largest-
        bucket chunks, then a tail rounded up to a bucket. Raises
        PromptTooLong when no tail bucket fits inside max_len."""
        big = self.buckets[-1]
        if n >= self.max_len:
            raise PromptTooLong(
                f"prompt_len {n} leaves no room to decode in max_len "
                f"{self.max_len}")
        plan = []
        done = 0
        while n - done > big:
            plan.append((big, big))
            done += big
        tail = n - done
        t = self._tail_bucket(tail)
        if t is None or done + t > self.max_len:
            raise PromptTooLong(
                f"prompt_len {n}: tail {tail} after {done} chunked tokens "
                f"fits no bucket within max_len {self.max_len}")
        plan.append((tail, t))
        return plan

    def _validate_submit(self, prompt, temperature, adapter, top_k, top_p,
                         presence_penalty, frequency_penalty, seed, stop,
                         deadline_s, tenant):
        """Every submit()-time argument check, factored out so the
        disaggregated coordinator (serving/disagg.py) can reject a bad
        request EAGERLY — on the caller's thread, before the job enters
        the prefill queue — instead of poisoning the engine-loop thread
        at dispatch time. Raises exactly what submit() would; returns the
        normalized (temperature, top_k, top_p, presence, frequency,
        folded_seed, stop_seqs, adapter_id) tuple submit() enqueues."""
        import math

        # a NaN/inf/huge value would blow up later INSIDE the engine loop
        # thread (wave packing), killing serving for every request
        if not (math.isfinite(temperature) and 0 <= temperature <= 100):
            raise ValueError("temperature must be finite and in [0, 100]")
        top_k = int(top_k)
        if not 0 <= top_k <= self.sample_k_max:
            raise ValueError(
                f"top_k must be 0..{self.sample_k_max} (the engine's "
                "static sample_k_max candidate window)")
        top_p = float(top_p)
        if not (math.isfinite(top_p) and 0 < top_p <= 1):
            raise ValueError("top_p must be in (0, 1]")
        presence_penalty = float(presence_penalty)
        frequency_penalty = float(frequency_penalty)
        for name, v in (("presence_penalty", presence_penalty),
                        ("frequency_penalty", frequency_penalty)):
            if not (math.isfinite(v) and -2 <= v <= 2):
                raise ValueError(f"{name} must be finite and in [-2, 2]")
        if seed is not None:
            if not isinstance(seed, int) or isinstance(seed, bool) \
                    or seed < 0:
                raise ValueError("seed must be a non-negative int")
            seed = _fold_seed24(seed)   # f32-exact; deterministic mixing
        stop_seqs: list[list[int]] = []
        for ss in (stop or ()):
            seq = [int(t) for t in ss]
            if not seq or len(seq) > 64:
                raise ValueError("each stop sequence must be 1..64 tokens")
            stop_seqs.append(seq)
        if len(stop_seqs) > 8:
            raise ValueError("at most 8 stop sequences per request")
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError("deadline_s must be positive")
        aid = 0
        if adapter is not None:
            if adapter not in self._adapter_idx:
                raise ValueError(
                    f"unknown adapter {adapter!r}; "
                    f"loaded: {sorted(self._adapter_idx)}")
            aid = self._adapter_idx[adapter]
        if tenant is not None and (not isinstance(tenant, str)
                                   or not 1 <= len(tenant) <= 256):
            # the length cap pairs with MAX_TENANTS: names persist in
            # _tenant_idx for the engine's lifetime, so both the count
            # AND the bytes must be bounded against adversarial clients
            raise ValueError("tenant must be a string of 1..256 chars")
        if len(prompt) > self.buckets[-1]:
            # chunked prefill: validate the chain now (fail at submit, not
            # mid-serve); the scheduler sees the largest bucket — it only
            # uses the length for bucket choice, the engine keeps the truth
            self._chunk_plan(len(prompt))
        return (temperature, top_k, top_p, presence_penalty,
                frequency_penalty, seed, stop_seqs, aid)

    def submit(self, prompt: Sequence[int], max_new_tokens: int = 32,
               temperature: float = 0.0,
               adapter: str | None = None,
               top_k: int = 0, top_p: float = 1.0,
               presence_penalty: float = 0.0,
               frequency_penalty: float = 0.0,
               seed: int | None = None,
               stop: Sequence[Sequence[int]] | None = None,
               deadline_s: float | None = None,
               tenant: str | None = None,
               trace: str | None = None) -> int:
        """Queue one request. top_k (0 = off) / top_p (1.0 = off) filter
        the sampled distribution inside the compiled programs (only when
        temperature > 0 — greedy rows stay bit-exact argmax).
        presence/frequency penalties (OpenAI [-2, 2]; 0 = off) are logit
        edits over the request's GENERATED tokens (the vLLM convention),
        applied inside the compiled programs before temperature/filters —
        they affect greedy requests too (penalized argmax). Nonzero
        penalties are quantized to milli units with a floor of ±1 milli
        (like the top_p micro guard): |v| < 0.0005 stays a minimal
        penalty instead of silently turning off. `seed` makes
        temp>0 sampling reproducible: the row's PRNG keys derive from
        (seed, position) alone, independent of slot, batch composition,
        decode chunking, or engine restarts. Seeds ride the f32 sampling
        row, so they are folded onto 24 bits via a splitmix64 mixing
        hash (_fold_seed24): distinct seeds can collide (~2^-24 per
        pair — unavoidable at this width), but unlike a plain modulus
        the colliding pairs are not predictable from the seed values,
        and the fold is deterministic so a given seed replays the same
        stream forever. `stop`: token-id sequences;
        generation ends (finish_reason "stop") when the output ends with
        one, and the matched sequence is excluded from the result (OpenAI
        semantics; matching is host-side at chunk boundaries, so at most
        one decode chunk of surplus is computed). `deadline_s`:
        wall-clock budget; past it the request is cancelled at the next
        chunk boundary (finish_reason "cancelled"). `tenant`: optional
        tenant name — requests of the same tenant share a scheduler queue
        and the max-min fair pop / admission caps (set_tenant_limits)
        apply per tenant; None rides the anonymous tenant-0 queue."""
        try:
            (temperature, top_k, top_p, presence_penalty,
             frequency_penalty, seed, stop_seqs, aid) = \
                self._validate_submit(prompt, temperature, adapter, top_k,
                                      top_p, presence_penalty,
                                      frequency_penalty, seed, stop,
                                      deadline_s, tenant)
        except PromptTooLong:
            if len(prompt) > self.buckets[-1]:
                # bump the scheduler's rejected counter (the operator
                # metric) but surface the chunk-aware message, not the
                # scheduler's generic "exceeds buckets"
                with self._submit_lock:
                    try:
                        self.scheduler.submit(len(prompt), max_new_tokens,
                                              time.monotonic(),
                                              tenant=self._tenant_id(tenant))
                    except PromptTooLong:
                        pass
            raise
        sched_len = min(len(prompt), self.buckets[-1])
        with self._submit_lock:
            req_id = self.scheduler.submit(sched_len, max_new_tokens,
                                           time.monotonic(),
                                           tenant=self._tenant_id(tenant))
            self._prompts[req_id] = list(prompt)
            if tenant is not None:
                self._req_tenant[req_id] = tenant
            self._results[req_id] = []
            self._logprobs[req_id] = []
            if self.logprobs_topk:
                self._toplogprobs[req_id] = []
            self._max_new[req_id] = max_new_tokens
            self._req_samp[req_id] = (
                float(temperature), top_k, top_p, presence_penalty,
                frequency_penalty, -1 if seed is None else seed)
            if stop_seqs:
                self._req_stop[req_id] = stop_seqs
            if deadline_s is not None:
                self._deadlines[req_id] = time.monotonic() + deadline_s
            if aid:
                self._req_aids[req_id] = aid
            self._req_plen[req_id] = len(prompt)
            self._submit_t[req_id] = time.monotonic()
            if trace is not None:
                self._req_trace[req_id] = trace
        obs_metrics.REQUESTS.inc(component=self.role, event="submitted")
        return req_id

    #: bound on distinct tenant names one engine tracks: the OpenAI
    #: `user` field is client-controlled, so an unbounded name->id map
    #: would be a memory leak an adversarial client can drive. Past the
    #: cap, new names share the anonymous tenant-0 queue — degraded
    #: fairness for the overflow tail, never unbounded growth.
    MAX_TENANTS = 65536

    def _tenant_id(self, tenant: str | None) -> int:
        """Tenant name -> stable scheduler id. MUST be called under
        _submit_lock: the len()-based id assignment has to be atomic
        with the insert, or two first-requests from distinct tenants
        could mint the same id and permanently merge their fairness
        queues and admission quotas."""
        if tenant is None:
            return 0
        tid = self._tenant_idx.get(tenant)
        if tid is not None:
            return tid
        if len(self._tenant_idx) >= self.MAX_TENANTS:
            return 0
        tid = len(self._tenant_idx) + 1
        self._tenant_idx[tenant] = tid
        return tid

    def cancel(self, req_id: int) -> bool:
        """Ask the engine to drop a request; takes effect at the NEXT
        chunk boundary (the engine thread applies pending cancellations at
        the top of step(), so the freed slot is refillable by the very
        next prefill wave). Thread-safe; callable from server/SSE threads.
        Returns True if the request was still in flight."""
        with self._submit_lock:
            if req_id in self._done or req_id not in self._results:
                return False
            self._cancel_pending.append(req_id)
            return True

    def _apply_cancellations(self) -> None:
        """Engine-thread only (top of step()): drain queued cancellations
        and expired deadlines, free their scheduler state, and mark them
        finished with reason "cancelled"."""
        now = time.monotonic()
        with self._submit_lock:
            pending = self._cancel_pending
            self._cancel_pending = []
            pending += [r for r, dl in self._deadlines.items()
                        if now >= dl and r not in self._done]
            for rid in dict.fromkeys(pending):   # dedup, keep order
                if rid in self._done or rid not in self._results:
                    continue
                self.scheduler.cancel(rid)
                self._finish_reasons[rid] = "cancelled"
                self._finish_t[rid] = now
                self._done.add(rid)
                self._cancelled_count += 1
                self._prompts.pop(rid, None)
                self._max_new.pop(rid, None)
                self._req_samp.pop(rid, None)
                self._req_stop.pop(rid, None)
                self._req_aids.pop(rid, None)
                self._deadlines.pop(rid, None)
                self._obs_finish(rid)

    def step(self) -> bool:
        """One engine iteration: a prefill wave or a batched decode.
        False = idle.

        All queued prefills drain into per-bucket BATCHED programs (one
        dispatch per bucket group) and every wave dispatches before any
        token fetch, so a burst of n arrivals pays ~one program dispatch +
        one RTT instead of n of each. Exception: prompts longer than the
        largest bucket run as per-request chained dispatches (2 per chunk
        boundary) — long-prompt TTFT scales with the chain length.

        Chunk boundary = here: pending cancellations and expired deadlines
        are applied first, so a freed slot is refillable by this very
        step's prefill wave."""
        self._apply_cancellations()
        with self._submit_lock:
            action = self.scheduler.next()
        if action is None:
            if self._pending is not None:
                self._drain_pending()   # the final chunk's tokens
                return True
            return False
        if isinstance(action, DecodeAction):
            self._do_decode()
            return True
        # prefill path: the in-flight chunk must land FIRST — its replay
        # frees slots/completes requests, and the device-side prefill that
        # follows overwrites any junk the chunk wrote into reused slots
        self._drain_pending()
        actions = [action]
        while len(actions) < self.n_slots:
            with self._submit_lock:
                nxt = self.scheduler.next()
            if not isinstance(nxt, PrefillAction):
                break   # Decode/None: dropping is safe — the decode pass
                        # re-derives from slot state on the next step()
            actions.append(nxt)
        t_prefill = time.monotonic()
        for a in actions:
            # phase epoch: the request's prefill left the queue now (a
            # chunked chain keeps its FIRST pop — the whole chain is one
            # prefill phase)
            self._prefill_start_t.setdefault(a.req_id, t_prefill)
        actions = self._admit_prefills(actions)
        if actions:
            self._run_prefill_actions(actions)
        return True

    def _admit_prefills(self, actions: list) -> list:
        """Admission hook between the scheduler pop and the wave
        dispatch. The slab engine admits everything — its KV rows are
        preallocated per slot, so a popped action is always fundable.
        The paged engine (serving/paged.py) overrides this to reserve
        KV blocks against the free-block watermark, run the radix
        eviction valve under pressure, and HOLD BACK actions it cannot
        fund yet (their slots stay assigned; the held prefill
        dispatches on a later step once blocks free up)."""
        return actions

    def _run_prefill_actions(self, actions: list) -> None:
        """Dispatch one admitted prefill burst and replay its tokens.
        Factored out of step() so the paged engine's held-action retry
        can dispatch without re-entering the scheduler pop."""
        # prompts longer than the largest bucket peel off into chained
        # chunked prefills; prefix-cache hits into continuation programs
        # (tail-only compute); everything else groups by bucket, one
        # batched program per group. All dispatches go out before any
        # token fetch.
        chunked: list[PrefillAction] = []
        short: list[PrefillAction] = []
        for a in actions:  # one-pass, identity-safe partition
            (chunked if len(self._prompts.get(a.req_id, ())) > a.bucket_len
             else short).append(a)
        cont: list[tuple] = []   # (action, match, p, t)
        normal: list[PrefillAction] = []
        if self.prefix_cache_enabled:
            for a in short:
                hit = self._prefix_lookup(a)
                (cont.append((a,) + hit) if hit is not None
                 else normal.append(a))
        else:
            normal = short
        groups: dict[int, list[PrefillAction]] = {}
        for a in normal:
            groups.setdefault(a.bucket_len, []).append(a)
        bt = self.prefix_block_tokens
        cont_groups: dict[tuple[int, int], list] = {}
        for a, m, p, t in cont:
            # materialize the pinned chain into the program's prefix
            # arrays (truncated to p when the legality clamp shortened
            # the match); the pin holds until after the dispatch below
            cont_groups.setdefault((p, t), []).append(
                (a, self._materialize_prefix(m.payloads[:p // bt])))
        dispatched = [(wave, self._dispatch_prefill_wave(bucket, wave))
                      for bucket, wave in groups.items()]
        dispatched += [([a for a, _ in pairs],
                        self._dispatch_prefill_cont_wave(p, t, pairs))
                       for (p, t), pairs in cont_groups.items()]
        dispatched += [([a], self._dispatch_chunked_prefill(a))
                       for a in chunked]
        # hit bookkeeping + unpin AFTER every dispatch went out: the
        # committed accounting records only reuse that actually rode a
        # continuation program
        for a, m, p, t in cont:
            self._prefix_hits += 1
            self._cached_prefix[a.req_id] = p
            self.kvcache.record_hit(self._req_tenant.get(a.req_id), p)
            self._prefill_reused_tokens += p
            self._prefill_computed_tokens += \
                len(self._prompts[a.req_id]) - p
            self.kvcache.release(m)
        if self.prefix_cache_enabled:
            # bank fresh prefix blocks BEFORE the fetch loop: recording a
            # request's final token pops its prompt, and extraction only
            # needs the (device-ordered) prefill to have been dispatched.
            # Continuation hits bank too — a multi-turn session's new
            # suffix blocks extend the cached chain (dedup skips the
            # already-cached prefix).
            for wave, _ in dispatched:
                for a in wave:
                    self._bank_prefix_blocks(a)
        for wave, out in dispatched:
            out_np = np.asarray(out)   # one fetch per wave [W, out_cols]
            for i, a in enumerate(wave):
                # true length, not action.prompt_len: a chunked request's
                # scheduler-visible length was clamped to the largest bucket
                self._host_lengths[a.slot] = len(self._prompts[a.req_id])
                if self._spec_adapt is not None:
                    # new occupant: optimistic draft length until its own
                    # rounds re-anchor the slot's acceptance EMA
                    self._spec_adapt.reset_slot(a.slot)
                tok, lp, top = self._unpack_out(out_np[i])
                self._record_token(a.req_id, a.slot, tok, lp, top,
                                   first_token=True)

    def _chunk_plan_from(self, n: int, start: int
                         ) -> list[tuple[int, int]] | None:
        """Chunk schedule for the UNCOVERED tokens [start, n) of a long
        prompt: [(chunk_len, program_len), ...] — full largest-bucket
        chunks, then a tail rounded up to a bucket. None when some
        boundary's continuation (p = tokens done so far) cannot fit
        inside max_len."""
        big = self.buckets[-1]
        plan = []
        done = start
        while n - done > big:
            if done + big > self.max_len:
                return None
            plan.append((big, big))
            done += big
        t = self._tail_bucket(n - done)
        if t is None or done + t > self.max_len:
            return None
        plan.append((n - done, t))
        return plan

    def _dispatch_chunked_prefill(self, action) -> Any:
        """Chained prefill for a prompt longer than the largest bucket:
        the first chunk runs the ordinary bucket prefill, then each
        further chunk extracts the accumulated slot KV and runs a
        continuation program against it (the prefix-reuse machinery,
        aimed at the slot's own rows). Radix composition: when the
        prompt's leading blocks are cached (the shared-system-prompt
        case) the chain STARTS at the longest reusable prefix instead of
        token 0 — possibly replacing the full first prefill and several
        chain links at once. One request = len(plan)+1 dispatches; the
        chain's programs compile lazily on the first long prompt — a
        cold start the docstring of warmup() points at. Returns the
        next-token device array [1]."""
        prompt = self._prompts[action.req_id]
        n = len(prompt)
        slot = action.slot
        tail = self._row_tail(action.req_id)
        big = self.buckets[-1]
        bt = self.prefix_block_tokens
        tenant = self._req_tenant.get(action.req_id)
        done = 0
        pending = None
        if self.prefix_cache_enabled and n - 1 >= bt:
            m = self.kvcache.match(
                prompt, max_tokens=n - 1,
                namespace=self._req_aids.get(action.req_id, 0))
            done = m.tokens
            # shrink the reused prefix until the remaining chain is
            # schedulable (every boundary fits inside max_len)
            while done > 0 and self._chunk_plan_from(n, done) is None:
                done -= bt
            if done > 0:
                pending = self._materialize_prefix(
                    m.payloads[:done // bt])
                self._prefix_hits += 1
                self._cached_prefix[action.req_id] = done
                self.kvcache.record_hit(tenant, done)
                self._prefill_reused_tokens += done
            else:
                self.kvcache.record_miss(tenant)
                self._prefix_misses += 1
            self.kvcache.release(m)
        self._prefill_computed_tokens += n - done
        if done == 0:
            packed = self._pack_rows(1, big,
                                     [(prompt[:big], slot, big) + tail])
            (self.cache, self.lengths, self.last_tokens, self.samp,
             self.rng_key, out) = self._prefill_fn(big, 1)(
                self.params, self.cache, self.lengths, self.last_tokens,
                self.samp, self.rng_key, self._put(packed),
                *self._extra())
            done = big
        plan = self._chunk_plan_from(n, done) or []
        for chunk_len, t in plan:
            ek, ev = (pending if pending is not None
                      else self._extract_fn(done)(self.cache, slot))
            pending = None
            # the chain boundary is a continuation with the request's OWN
            # prefix (p == done), so the row layout comes from the same
            # helper the cont waves use
            row_toks = self._cont_row_tokens(
                list(prompt[:done + chunk_len]), done, t)
            packed = self._pack_rows(1, t + (done if self.spec else 0),
                                     [(row_toks, slot,
                                       done + chunk_len) + tail])
            (self.cache, self.lengths, self.last_tokens, self.samp,
             self.rng_key, out) = self._cont_fn(done, t, 1)(
                self.params, self.cache, self.lengths, self.last_tokens,
                self.samp, self.rng_key, self._put(packed), ek, ev,
                *self._extra())
            done += chunk_len
        return out

    def run_until_idle(self) -> None:
        while self.step():
            pass

    def warmup(self) -> None:
        """Execute every program in the menu once (each bucket × each
        power-of-two wave width, plus decode) so no request ever pays XLA
        compile time. Must run before serving traffic: a cold width means
        a whole burst waits ~seconds on the compiler. Slot state is junk
        during warmup and reset after; call only while idle.

        NOT pre-warmed: the chunked-prefill chain programs (extract +
        continuation per chunk boundary) — the first prompt longer than
        the largest bucket pays their compile, later ones are warm."""
        ex = self._row_extra
        for bucket in self.buckets:
            width = 1
            while True:   # every power of two through next-pow2(n_slots):
                # a wave of n_slots actions pads UP to that width, so for
                # e.g. n_slots=6 width 8 must be warm too
                packed = np.zeros((width, bucket + ex), np.int32)
                packed[:, :2] = 1   # token + prompt_len floor
                packed[:, -ex] = np.arange(width) % self.n_slots
                packed[:, -ex + 1] = 1
                packed[:, -ex + 7] = -1   # unseeded sentinel
                (self.cache, self.lengths, self.last_tokens, self.samp,
                 self.rng_key, _) = self._prefill_fn(bucket, width)(
                    self.params, self.cache, self.lengths,
                    self.last_tokens, self.samp, self.rng_key,
                    self._put(packed), *self._extra())
                if width >= self.n_slots:
                    break
                width *= 2
        if self.prefix_cache_enabled:
            # continuation menu: (block-multiple prefix, tail bucket,
            # width) combos, plus the per-prefix extract programs. Radix
            # hits reuse ANY block multiple up to the largest bucket
            # (longer reused prefixes belong to the chunked chain and
            # compile lazily like the rest of it). Only the first
            # `warm_cont_pairs` pairs are pre-compiled (the menu grows
            # with buckets[-1]/block — see __init__); colder pairs
            # compile lazily on their first hit.
            bt = self.prefix_block_tokens
            pairs = [(p, t) for p in range(bt, self.buckets[-1] + 1, bt)
                     for t in self.buckets if p + t <= self.max_len]
            if self.warm_cont_pairs is not None:
                pairs = pairs[:self.warm_cont_pairs]
            # the banking path's raw-extract programs are cheap slice
            # jits, but a cold one still stalls the engine thread
            # mid-replay — warm every block multiple the banker can ask
            # for (aligned prompt prefixes up to max_len). The paged
            # engine banks block ids (no extraction) and skips this.
            if self._bank_uses_raw_extract:
                for p in range(bt, self.max_len, bt):
                    self._extract_raw_fn(p)(self.cache, 0)
            extracts = {}
            for p, t in pairs:
                if p not in extracts:
                    extracts[p] = self._extract_fn(p)(self.cache, 0)
                ek, ev = extracts[p]
                width = 1
                while True:
                    cols = t + (p if self.spec else 0) + ex
                    packed = np.zeros((width, cols), np.int32)
                    packed[:, 0] = 1
                    packed[:, -ex] = np.arange(width) % self.n_slots
                    packed[:, -ex + 1] = p + 1  # last-row index stays valid
                    packed[:, -ex + 7] = -1   # unseeded sentinel
                    kw, vw = self._stack_prefix([(ek, ev)] * width)
                    (self.cache, self.lengths, self.last_tokens,
                     self.samp, self.rng_key, _) = \
                        self._cont_fn(p, t, width)(
                            self.params, self.cache, self.lengths,
                            self.last_tokens, self.samp, self.rng_key,
                            self._put(packed), kw, vw, *self._extra())
                    if width >= self.n_slots:
                        break
                    width *= 2
        chunks, k = [], 1
        while k <= self.decode_chunk:
            chunks.append(k)
            k *= 2
        spans = self._span_menu()
        combos = [(c, s) for c in chunks for s in spans]
        if len(combos) > 16:
            # long-cache engines: the full (chunk x span) grid is too many
            # compiles — warm every chunk at full span plus the workhorse
            # chunk at every span; cold combos compile lazily on first use
            combos = ([(c, self.max_len) for c in chunks]
                      + [(chunks[-1], s) for s in spans[:-1]])
        out = None
        # spec mode dispatches _spec_fn instead of _decode_fn — warm THAT
        # menu (the plain decode menu would be dead weight)
        fn = self._spec_fn if self.spec else self._decode_fn
        for c, span in combos:
            (self.cache, self.lengths, self.last_tokens, self.samp,
             self.rng_key, out) = fn(c, span)(
                self.params, self.cache, self.lengths, self.last_tokens,
                self.samp, self.rng_key,
                self._put(np.zeros((self.n_slots,), bool)),
                *self._extra())
        if self._spec_adapt is not None:
            # adaptive draft length: warm each sub-k_max menu k at the
            # workhorse chunk and the drain-tail chunk (full span only —
            # the rest of the (chunk, span, k) cube would explode compile
            # time; cold members fall back to the static-k program at
            # dispatch, exactly like cold spans fall back to full span)
            for kd in self._spec_adapt.menu[:-1]:
                for c in {chunks[-1], 1}:
                    (self.cache, self.lengths, self.last_tokens, self.samp,
                     self.rng_key, out) = self._spec_fn(
                        c, self.max_len, kd)(
                        self.params, self.cache, self.lengths,
                        self.last_tokens, self.samp, self.rng_key,
                        self._put(np.zeros((self.n_slots,), bool)),
                        *self._extra())
        float(np.asarray(out).flat[0])  # sync: compile + execute finished
        # (axon-safe: a value fetch, not block_until_ready)
        # reset via _put, not zeros_like: under a mesh the reset arrays must
        # carry the same committed replicated sharding the programs were
        # traced with, or the first live request retraces (= recompiles)
        self.lengths = self._put(np.zeros((self.n_slots,), np.int32))
        self.last_tokens = self._put(np.zeros((self.n_slots,), np.int32))
        self.samp = self._put(self._samp_reset())
        self._host_lengths[:] = 0
        self._pending = None
        self._inflight[:] = 0
        self._active_host = None
        self._active_dev = None
        self._decode_chunk_warm = self.decode_chunk
        self._warmed = True

    def close(self) -> None:
        """Release device state NOW. The engine is cyclic (compiled-
        program dicts hold jit(partial(self._...)) objects that reference
        the engine), so `del engine` alone leaves the KV cache + params
        refs alive until a full gc pass — on a 16 GiB chip that is the
        difference between the next engine fitting or not. close()
        breaks the cycles and drops the big buffers eagerly."""
        import gc

        for d in (self._prefill_fns, self._decode_fns, self._spec_fns,
                  self._cont_fns, self._extract_fns,
                  self._extract_raw_fns):
            d.clear()
        self.kvcache = None   # block payloads hold the only device refs
        self._pending = None
        self._active_dev = None
        self._active_host = None
        self.cache = None
        self.params = None
        gc.collect()

    def _obs_publish(self) -> None:
        """Scrape hook body: refresh this engine's queue-depth gauges
        just before a /metrics render (see obs.metrics.add_scrape_hook;
        exceptions are swallowed by the hook runner, so a closed engine
        can't poison a scrape)."""
        s = self.scheduler.stats()
        obs_metrics.SCHED_QUEUED.set(s.queued, engine=self.role)
        obs_metrics.SCHED_ACTIVE.set(s.active, engine=self.role)
        obs_metrics.INFLIGHT.set(s.queued + s.active,
                                 component=self.role)
        # resolved attention impls as info-style gauges (ISSUE 20): one
        # series per (engine, phase, impl), value 1 — a scrape can alert
        # on a fleet member silently falling back to the einsum path
        obs_metrics.ATTENTION_IMPL.set(
            1, engine=self.role, phase="decode",
            impl=llama.resolve_decode_attn(self.cfg))
        obs_metrics.ATTENTION_IMPL.set(
            1, engine=self.role, phase="prefill",
            impl=llama.resolve_prefill_attn(self.cfg))
        if self.kvcache is not None:
            st = self.kvcache.stats()
            obs_metrics.KV_FREE_BLOCKS.set(st["free_blocks"],
                                           engine=self.role)
            obs_metrics.KV_WATERMARK_FRAC.set(st["watermark_frac"],
                                              engine=self.role)

    def is_done(self, req_id: int) -> bool:
        return req_id in self._done

    def result(self, req_id: int) -> list[int]:
        if req_id not in self._done:
            raise KeyError(f"request {req_id} not finished")
        return self._results[req_id]

    def result_logprobs(self, req_id: int) -> list[float]:
        """Per-token raw-model logprobs of result(req_id) (same length;
        the OpenAI `logprobs` surface)."""
        if req_id not in self._done:
            raise KeyError(f"request {req_id} not finished")
        return self._logprobs[req_id]

    def result_top_logprobs(self, req_id: int) -> list[dict[int, float]]:
        """Per-position top-N alternative logprobs ({token_id: logprob});
        requires the engine to be built with logprobs_topk > 0."""
        if not self.logprobs_topk:
            raise ValueError("engine built with logprobs_topk=0")
        if req_id not in self._done:
            raise KeyError(f"request {req_id} not finished")
        return self._toplogprobs[req_id]

    def partial_result(self, req_id: int) -> list[int]:
        """Tokens generated so far (streaming consumers poll this while
        the request runs). Snapshot copy: the engine thread appends."""
        return list(self._results.get(req_id, ()))

    def partial_logprobs(self, req_id: int) -> list[float]:
        """Logprobs of the tokens generated so far (streaming twin of
        result_logprobs)."""
        return list(self._logprobs.get(req_id, ()))

    def finish_reason(self, req_id: int) -> str:
        """Why a finished request stopped: "stop" (EOS) or "length"
        (max-new-tokens / cache room). Read before release()."""
        return self._finish_reasons.get(req_id, "length")

    def release(self, req_id: int) -> None:
        """Drop all per-request state. Long-lived servers MUST call this
        after reading result(), or per-request dicts grow without bound."""
        self._done.discard(req_id)
        self._results.pop(req_id, None)
        self._logprobs.pop(req_id, None)
        self._toplogprobs.pop(req_id, None)
        self._submit_t.pop(req_id, None)
        self._first_token_t.pop(req_id, None)
        self._finish_t.pop(req_id, None)
        self._finish_reasons.pop(req_id, None)
        self._req_tenant.pop(req_id, None)
        self._cached_prefix.pop(req_id, None)
        self._req_plen.pop(req_id, None)
        self._prefill_start_t.pop(req_id, None)
        self._req_trace.pop(req_id, None)
        self._decode_mark.pop(req_id, None)

    def generate(self, prompt: Sequence[int],
                 max_new_tokens: int = 32,
                 temperature: float = 0.0,
                 adapter: str | None = None, **kw) -> list[int]:
        rid = self.submit(prompt, max_new_tokens, temperature,
                          adapter=adapter, **kw)
        while not self.is_done(rid):
            if not self.step():
                raise RuntimeError("engine idle with request outstanding")
        return self.result(rid)

    def ttft_seconds(self, req_id: int) -> float | None:
        """Submit→first-token latency for one request (None until then)."""
        if req_id not in self._first_token_t:
            return None
        return self._first_token_t[req_id] - self._submit_t[req_id]

    def request_timing(self, req_id: int) -> dict[str, Any]:
        """Wall-clock record for one request (the loadgen runner's SLO
        input): submit / first-token / finish instants (time.monotonic;
        None until they happen), tenant, tokens delivered so far, and
        the prefix-reuse fields — prompt_len, cached_prefix_len (KV
        tokens reused from the radix cache; 0 until the prefill lands or
        with the cache off) and prefill_tokens (what was actually
        computed) — plus the explicit PHASE split (the disagg bench's
        interference attribution): queue_wait_ms (submit → the prefill
        leaving the queue), prefill_ms (queue exit → first token) and
        decode_ms (first token → finish), each None until its phase
        boundary lands. Read BEFORE release() — release drops all of
        it."""
        plen = self._req_plen.get(req_id)
        cached = self._cached_prefix.get(req_id, 0)
        sub = self._submit_t.get(req_id)
        pstart = self._prefill_start_t.get(req_id)
        first = self._first_token_t.get(req_id)
        fin = self._finish_t.get(req_id)

        def ms(a, b):
            return (round((b - a) * 1e3, 3)
                    if a is not None and b is not None else None)

        return {
            "submit_s": sub,
            "first_token_s": first,
            "finish_s": fin,
            "tenant": self._req_tenant.get(req_id),
            "n_tokens": len(self._results.get(req_id, ())),
            "prompt_len": plen,
            "cached_prefix_len": cached,
            "prefill_tokens": (plen - cached if plen is not None
                               else None),
            "queue_wait_ms": ms(sub, pstart),
            "prefill_ms": ms(pstart, first),
            "decode_ms": ms(first, fin),
        }

    def cached_tokens(self, req_id: int) -> int:
        """Prompt tokens whose KV was reused from the prefix cache for
        this request (the OpenAI usage `cached_tokens` surface). 0 until
        the prefill lands, with the cache off, or on a miss."""
        return self._cached_prefix.get(req_id, 0)

    def set_tenant_limits(self, max_active_per_tenant: int = 0,
                          max_queued_per_tenant: int = 0) -> None:
        """Per-tenant fairness/admission knobs, forwarded to the scheduler
        (both twins): a soft work-conserving share cap on decode slots and
        a hard admission cap on queued requests (over it, submit raises
        TenantOverQuota). 0 disables either."""
        self.scheduler.set_fairness(max_active_per_tenant,
                                    max_queued_per_tenant)

    @property
    def decode_chunk_max(self) -> int:
        """Largest decode chunk the warmed program menu supports (the
        set_decode_chunk clamp; the SLO controller's upper bound)."""
        return self._decode_chunk_warm

    def set_decode_chunk(self, chunk: int) -> int:
        """Re-pick the decode chunk length at runtime (the SLO-aware
        `ttft_target_ms` control surface — loadgen/control.py): a prefill
        wave must drain the in-flight chunk first, so TTFT carries ~one
        chunk of decode wall time, while throughput mildly prefers longer
        chunks (measured at 8B/32 slots: chunk 8 = 1055 tok/s / p50
        ~465 ms; chunk 4 = 990 tok/s / p50 ~217 ms). Applied at the next
        chunk boundary — _do_decode reads self.decode_chunk per dispatch.
        After warmup the value is clamped to the warmed menu (powers of
        two up to the construction-time decode_chunk) so live traffic
        never waits on the XLA compiler. Returns the applied value."""
        chunk = max(1, int(chunk))
        if self._warmed:
            chunk = min(chunk, self._decode_chunk_warm)
        self.decode_chunk = chunk
        return chunk

    def mesh_info(self) -> dict[str, Any]:
        """The /healthz `mesh` section (ISSUE 14 satellite): layout name,
        axis names/sizes, device count, and params bytes — so a fleet
        operator can tell a single-chip replica from a tp slice from a
        tp×pp stage-sharded one without a device round-trip. The
        stage-sharded engine overrides this with its per-stage view."""
        params_bytes = (int(sum(l.nbytes
                                for l in jax.tree.leaves(self.params)))
                        if self.params is not None else 0)
        if self.mesh is None:
            return {"layout": "single", "axes": {}, "device_count": 1,
                    "params_bytes": params_bytes}
        from kubeflow_tpu.parallel.mesh import mesh_shape

        shape = mesh_shape(self.mesh)
        axes = {k: v for k, v in shape.items() if v > 1}
        return {"layout": "tensor" if axes.get("tensor", 1) > 1
                else "mesh",
                "axes": axes,
                "device_count": int(math.prod(shape.values())),
                "params_bytes": params_bytes}

    def metrics(self) -> dict[str, Any]:
        ttfts = list(self._ttft_window)  # survives release() of old requests
        s = self.scheduler.stats()
        out = {"queued": s.queued, "active": s.active,
               "completed": s.completed, "rejected": s.rejected,
               "cancelled": self._cancelled_count,
               "decode_chunk": self.decode_chunk,
               # the RESOLVED decode-attention impl (the A/B bench and
               # /healthz read this, so a record can never misreport
               # which kernel path produced its numbers)
               "decode_attention_impl": llama.resolve_decode_attn(self.cfg),
               # ...and its prefill twin (ISSUE 20): the impl the
               # prefill/continuation chunk programs run
               "prefill_attention_impl":
                   llama.resolve_prefill_attn(self.cfg),
               # which KV residency this engine runs (serving/paged.py
               # overrides to "paged" and adds the pool gauges)
               "kv_layout": self.kv_layout,
               "mesh": self.mesh_info()}
        out["prefill_tokens_computed"] = self._prefill_computed_tokens
        if self.prefix_cache_enabled and self.kvcache is not None:
            st = self.kvcache.stats()
            out["prefix_hits"] = self._prefix_hits
            out["prefix_misses"] = self._prefix_misses
            out["prefix_entries"] = st["blocks"]
            looked = self._prefix_hits + self._prefix_misses
            out["prefix_cache"] = {
                **st,
                "request_hits": self._prefix_hits,
                "request_misses": self._prefix_misses,
                "request_hit_rate": (round(self._prefix_hits / looked, 4)
                                     if looked else None),
                "prefill_tokens_computed": self._prefill_computed_tokens,
                "prefill_tokens_saved": self._prefill_reused_tokens,
            }
        if self.adapters is not None:
            out["adapters_loaded"] = sorted(self._adapter_idx)
        if self._tenant_idx:
            out["tenants_seen"] = len(self._tenant_idx)
        if self.spec:
            out["spec_verify_rounds"] = self._spec_verifies
            out["spec_tokens_emitted"] = self._spec_tokens
            # 1.0 = no draft ever accepted (plain-decode cost); spec+1 =
            # every draft accepted — the effective per-round multiplier
            out["spec_tokens_per_round"] = round(
                self._spec_tokens / max(1, self._spec_verifies), 3)
            out["spec_draft_k_max"] = self.spec
            out["spec_est_round_tokens"] = round(
                self._est_round_tokens(), 3)
            if self._spec_adapt is not None:
                out["spec_draft_k_last"] = self._spec_last_k
                out["spec_accept_ema"] = round(
                    float(np.mean(self._spec_adapt.ema)), 3)
        if ttfts:
            out["ttft_p50_s"] = float(np.percentile(ttfts, 50))
            out["ttft_p99_s"] = float(np.percentile(ttfts, 99))
        return out

    # -- internals -----------------------------------------------------------

    def _extra(self) -> tuple:
        """Trailing program args: the adapter stack rides as an explicit
        argument (a closure would inline it into the HLO as constants)."""
        return () if self.adapters is None else (self.adapters,)

    @staticmethod
    def _pack_temp(temp: float) -> int:
        """Nearest-milli quantization; sub-milli temps still sample (floor
        of 1) rather than silently flipping to greedy. ONE rule for the
        full-prefill and continuation row layouts."""
        return max(1, round(temp * 1000)) if temp > 0 else 0

    @staticmethod
    def _pack_milli(v: float) -> int:
        """Signed nearest-milli quantization for the penalty columns with
        a floor of ±1 milli on nonzero values (the penalties' twin of the
        _pack_temp/top_p guards): a requested |v| < 0.0005 must stay a
        minimal penalty, not silently round to OFF (ADVICE r5)."""
        if v == 0:
            return 0
        q = round(v * 1000)
        return q if q else (1 if v > 0 else -1)

    def _row_tail(self, req_id: int) -> tuple:
        """The non-token row columns for one request: (temp, top_k, top_p,
        presence, frequency, seed[, adapter_idx]) — ONE source for every
        wave-packing call site."""
        tail = self._req_samp.get(req_id, (0.0, 0, 1.0, 0.0, 0.0, -1))
        if self.adapters is not None:
            tail = tail + (self._req_aids.get(req_id, 0),)
        return tail

    def _pack_rows(self, width: int, bucket: int, rows) -> np.ndarray:
        """[tokens ++ slot ++ prompt_len ++ temp_milli ++ top_k ++
        top_p_micro ++ presence_milli ++ freq_milli ++ seed(, aid)] per
        row, padded up to `width` by repeating the last row (idempotent
        duplicate writes). rows: list of (tokens, slot, prompt_len, temp,
        top_k, top_p, presence, frequency, seed[, adapter_idx])."""
        ex = self._row_extra
        padded = list(rows) + [rows[-1]] * (width - len(rows))
        packed = np.zeros((width, bucket + ex), np.int32)
        for i, row in enumerate(padded):
            toks, slot, plen, temp, topk, topp = row[:6]
            pres, freq, seed = row[6:9]
            packed[i, :len(toks)] = toks
            packed[i, -ex] = slot
            packed[i, -ex + 1] = plen
            packed[i, -ex + 2] = self._pack_temp(temp)
            packed[i, -ex + 3] = int(topk)
            # micro quantization with a floor of 1 (like _pack_temp): a
            # sub-micro top_p must stay a maximal filter, not flip to OFF
            packed[i, -ex + 4] = (1_000_000 if topp >= 1
                                  else max(1, round(topp * 1e6)))
            packed[i, -ex + 5] = self._pack_milli(pres)
            packed[i, -ex + 6] = self._pack_milli(freq)
            packed[i, -ex + 7] = int(seed)
            if ex == 9:
                packed[i, -1] = row[9] if len(row) > 9 else 0
        return packed

    def _cont_row_tokens(self, prompt: list[int], p: int, t: int):
        """A continuation row's token columns: the tail (prompt[p:p+...],
        padded to the tail bucket by _pack_rows) — plus, in speculative
        mode, the p prefix tokens appended after a pad-to-t, so the
        compiled program can mirror them into the history buffer."""
        tail = prompt[p:]
        if not self.spec:
            return tail
        return tail + [0] * (t - len(tail)) + prompt[:p]

    def _dispatch_prefill_cont_wave(self, p: int, t: int, pairs):
        """Dispatch ONE batched continuation prefill for all hits sharing
        (prefix length, tail bucket) — a shared-prefix burst costs one
        packed transfer + one dispatch, mirroring _dispatch_prefill_wave.
        pairs: list of (action, materialized (k, v) prefix); returns [W]
        device tokens."""
        width = 1
        while width < len(pairs):
            width *= 2
        padded = list(pairs) + [pairs[-1]] * (width - len(pairs))
        rows = [(self._cont_row_tokens(self._prompts[a.req_id], p, t),
                 a.slot, a.prompt_len) + self._row_tail(a.req_id)
                for a, _ in padded]
        packed = self._pack_rows(width, t + (p if self.spec else 0), rows)
        k_prefix, v_prefix = self._stack_prefix([e for _, e in padded])
        (self.cache, self.lengths, self.last_tokens, self.samp,
         self.rng_key, out) = self._cont_fn(p, t, width)(
            self.params, self.cache, self.lengths, self.last_tokens,
            self.samp, self.rng_key, self._put(packed),
            k_prefix, v_prefix, *self._extra())
        return out

    def _bank_prefix_blocks(self, action) -> None:
        """After a prefill (full, continuation, or chunked chain), cache
        the slot's block-aligned prompt-prefix KV. Probe first — a chain
        already cached end-to-end costs zero extraction — then extract
        the aligned prefix ONCE (device-to-device slice; nothing crosses
        the host) and hand the radix insert lazy per-block slices: only
        NEW blocks are sliced and stored."""
        prompt = self._prompts.get(action.req_id)
        if prompt is None:
            return
        bt = self.prefix_block_tokens
        aligned = (len(prompt) // bt) * bt
        ns = self._req_aids.get(action.req_id, 0)
        if aligned <= 0:
            return
        if self.kvcache.cached_prefix_len(
                prompt, max_tokens=aligned, namespace=ns) >= aligned:
            return
        parts = self._extract_raw_fn(aligned)(self.cache, action.slot)

        def payload(_i, s, e):
            return self._payload_slice(parts, s, e)

        self.kvcache.insert(prompt, payload, max_tokens=aligned,
                            tenant=self._req_tenant.get(action.req_id),
                            namespace=ns)

    def _dispatch_prefill_wave(self, bucket: int,
                               wave: list[PrefillAction]):
        """Dispatch one batched prefill over `wave`; returns the (device)
        next-token array [W] WITHOUT fetching, so several waves can
        pipeline. The wave is padded up to a power-of-two width by
        repeating its last action (idempotent duplicate writes), keeping
        the compiled-program menu small."""
        width = 1
        while width < len(wave):
            width *= 2
        # one packed transfer: [tokens ++ slot ++ prompt_len ++ sampling
        # columns] per row (a tunneled device pays ~an RTT per transfer)
        rows = [(self._prompts[a.req_id], a.slot, a.prompt_len)
                + self._row_tail(a.req_id) for a in wave]
        self._prefill_computed_tokens += sum(
            len(self._prompts[a.req_id]) for a in wave)
        packed = self._pack_rows(width, bucket, rows)
        (self.cache, self.lengths, self.last_tokens, self.samp,
         self.rng_key, out) = self._prefill_fn(bucket, width)(
            self.params, self.cache, self.lengths, self.last_tokens,
            self.samp, self.rng_key, self._put(packed), *self._extra())
        return out

    def _do_decode(self) -> None:
        """Scan-fused decode: K steps execute inside ONE compiled program
        (one dispatch + one token fetch for the whole chunk). On a
        tunneled/remote device the per-call round-trip (~100ms-class)
        dwarfs the per-token compute, so K-in-one-program is the
        difference between RTT-per-token and RTT-per-chunk.

        PIPELINED (pipeline_decode=True): the next chunk is DISPATCHED
        before the previous chunk's tokens are fetched, so the host-side
        fetch RTT + replay overlaps the device's execution of the new
        chunk — per-chunk wall time becomes max(device, host) instead of
        their sum (~106ms RTT measured against an 8B chunk). The cost: a
        slot that finishes mid-chunk burns at most ONE extra chunk of
        junk compute before the host notices, and planning uses lengths
        that lag the device by the in-flight chunk (tracked via
        _inflight).

        K = largest power of two <= decode_chunk that fits cache headroom
        (chunk writes KV rows L..L+K-1 for the fullest slot, which must
        stay < max_len). Slots may finish (EOS / max_new) mid-chunk: their
        surplus tokens are dropped host-side, and new arrivals wait at
        most one chunk for their prefill — decode_chunk bounds scheduling
        latency."""
        if self._pending is not None:
            # if the in-flight chunk's deliveries already satisfy every
            # active budget, OR the cache has no room for even one more
            # row past the in-flight writes (the out_of_room finish will
            # land at replay), another dispatch would be pure junk
            # compute — drain instead (this is what makes the final chunk
            # of a drain free under pipelining). Plain decode delivers
            # EXACTLY psteps per continuing slot; spec rounds deliver
            # 1..per_tok each, so the guard also drains when the LIKELY
            # spec delivery (observed live acceptance, optimism margin)
            # covers every budget — at high acceptance the follow-on
            # chunk is near-certain junk and one dispatch RTT is the
            # whole r3->r4 spec-throughput regression (VERDICT r4 weak
            # #3); at low acceptance the estimate stays small and the
            # pipeline keeps running.
            psr, psteps, _, _, _ = self._pending
            full = max((int(self._host_lengths[s] + self._inflight[s])
                        for s in range(self.n_slots) if psr[s] >= 0),
                       default=0) >= self.max_len
            need = [self._max_new[r] - len(self._results[r])
                    for r in psr if r >= 0 and r in self._max_new]
            likely = psteps * self._est_round_tokens() * 1.25
            if full or all(n <= psteps for n in need) or (
                    self.spec and all(n <= likely for n in need)):
                self._drain_pending()
                return
        slot_req = self._mask_unfunded(
            [self.scheduler.slot_request(s) for s in range(self.n_slots)])
        active = np.array([r >= 0 for r in slot_req], bool)
        if not active.any():
            # every live slot is admission-held (paged engine under
            # block pressure): nothing has KV to decode against yet
            return
        # adaptive draft length: the per-slot acceptance EMAs of the
        # DRAFTING slots (greedy, penalty-free — sampled/penalized rows
        # draft nothing by contract) set this round's k; a batch with no
        # drafting slot verifies at the smallest warmed k, near
        # plain-decode cost
        kd = self.spec or 0
        if self.spec and self._spec_adapt is not None:
            kd = self._spec_adapt.pick(
                [s for s, r in enumerate(slot_req)
                 if r >= 0 and self._draftable(r)])
        per_tok = (kd + 1) if self.spec else 1
        # in-flight credit: the pending chunk GUARANTEES psteps deliveries
        # to each slot it still owns, so the next chunk is sized for what
        # will remain after those land — without it a second chunk can be
        # sized past a request's true budget (junk compute at the tail)
        credit = [0] * self.n_slots
        if self._pending is not None:
            psr, psteps, _, _, _ = self._pending
            for s, r in enumerate(psr):
                if r >= 0 and r == slot_req[s]:
                    credit[s] = psteps
        remaining = max(max(1, self._max_new[r] - len(self._results[r])
                            - credit[s])
                        for s, r in enumerate(slot_req) if r >= 0)
        # planned-position accounting: rows already written by the
        # in-flight (unfetched) chunk count toward headroom and span
        planned = self._host_lengths + self._inflight
        headroom = self.max_len - int(
            max(planned[s] for s in range(self.n_slots) if active[s]))
        est = self._est_round_tokens()
        k = 1
        # doubling guard: the NEXT candidate (k*2 steps) must fit — a
        # spec round writes up to per_tok rows, plain decode exactly one;
        # spec sizing counts LIKELY tokens per round (est), not rounds,
        # so a high-acceptance engine stops growing once k rounds should
        # cover the largest remaining budget
        while (k * 2 <= self.decode_chunk
               and k * 2 * per_tok <= headroom
               and k * est < remaining):
            k *= 2
        # length-aware span: the chunk's last write lands at max_len-1 at
        # most; attend over the smallest power-of-two window covering every
        # active length through the chunk's end
        longest = int(max((planned[s] for s in range(self.n_slots)
                           if active[s]), default=0))
        span = self._pick_span(min(longest + k * per_tok, self.max_len))
        # after warmup, never hand live traffic to the XLA compiler: a
        # (chunk, span[, k]) combo outside the warmed menu (small tail
        # chunks at mid spans; adaptive ks at mid chunks — warmup covers
        # every chunk at FULL span with k_max, the workhorse chunk at
        # every span, and the sub-k_max menu at the workhorse and tail
        # chunks) falls back first to the full-span variant, then to the
        # static-k program. At 8B dims a cold compile is seconds; the
        # fallbacks cost ~nothing extra (full-span reads measured 20.1 vs
        # 19.8 ms/step; a too-long k only verifies dead draft positions).
        if self.spec:
            if self._warmed and (k, span, kd) not in self._spec_fns:
                if (k, self.max_len, kd) in self._spec_fns:
                    span = self.max_len
                else:
                    # static-k program at FULL span (every chunk is warm
                    # there). span must be full, not merely warm: the
                    # picked span only covers k*(kd+1) writes, and the
                    # static program advances up to k*(spec+1) rows —
                    # attending a too-short window would silently drop
                    # the newest context from late rounds' logits.
                    kd = self.spec
                    span = self.max_len
                    # the fallback k also writes more rows per round than
                    # the sizing assumed — shrink the chunk to stay
                    # inside the cache headroom (power-of-two chunks all
                    # warm at full span)
                    while k > 1 and k * (kd + 1) > headroom:
                        k //= 2
            fn = self._spec_fn(k, span, kd)
            per_tok = kd + 1
        else:
            if self._warmed and (k, span) not in self._decode_fns:
                span = self.max_len
            fn = self._decode_fn(k, span)
        self._spec_last_k = kd
        t_dispatch = time.perf_counter()
        (self.cache, self.lengths, self.last_tokens, self.samp,
         self.rng_key, out) = fn(
            self.params, self.cache, self.lengths, self.last_tokens,
            self.samp, self.rng_key, self._active_for(active),
            *self._extra())
        self._perf["dispatch_s"] += time.perf_counter() - t_dispatch
        self._perf["decode_chunks"] += 1
        self._perf["decode_steps"] += k
        # obs: aggregate counters only on this path (no span objects —
        # scripts/check_observability.py lints that invariant)
        self._decode_agg.note_step(int(active.sum()) * k * per_tok,
                                   steps=k)
        rows_added = np.where(active, k * per_tok, 0)
        self._inflight += rows_added
        prev = self._pending
        self._pending = (slot_req, k, out, rows_added, kd)
        if not self.pipeline_decode:
            self._drain_pending()
        elif prev is not None:
            self._replay(prev)

    def _mask_unfunded(self, slot_req: list[int]) -> list[int]:
        """Decode-planning hook: the paged engine masks slots whose
        prefill is admission-HELD (slot assigned by the scheduler, no KV
        funded yet) to -1, so chunk sizing, the active mask, and replay
        treat them as empty until their prefill lands. Slab engines have
        no held state — identity."""
        return slot_req

    def _constrain_cnt(self, cnt):
        """Pin the penalty-count layout under a mesh (see _shard_over)."""
        if self.mesh is None:
            return cnt
        return jax.lax.with_sharding_constraint(cnt, self._cnt_sh)

    def _draftable(self, req_id: int) -> bool:
        """True when the request's rows draft under speculation: greedy
        (temp == 0) and penalty-free — the same predicate the compiled
        program applies per row."""
        t = self._req_samp.get(req_id)
        return t is None or (t[0] <= 0 and t[3] == 0 and t[4] == 0)

    def _active_for(self, active: np.ndarray):
        """Device-resident decode active mask, re-uploaded only when the
        mask actually changes (slot assignments move at prefill/finish
        boundaries, not per chunk) — on a tunneled device the redundant
        per-chunk host->device transfer was ~an RTT of pure overhead."""
        if (self._active_host is None
                or not np.array_equal(active, self._active_host)):
            self._active_host = active.copy()
            self._active_dev = self._put(active)
            self._perf["active_uploads"] += 1
        return self._active_dev

    def perf_counters(self, reset: bool = False) -> dict[str, Any]:
        """Decode host-side attribution counters (dispatch wall, fetch+
        replay wall, chunk/step counts, active-mask uploads). The serving
        profiler (training/profiling.serving_decode_breakdown) reads these
        to fill the host buckets of the decode-step breakdown."""
        out = dict(self._perf)
        if reset:
            for key in self._perf:
                self._perf[key] = type(self._perf[key])(0)
        return out

    def _observe_round_tokens(self, n: int) -> None:
        """Fold one verify round's delivered-token count into the EMA the
        chunk sizing and drain heuristic consume."""
        if self._spec_round_ema is None:
            self._spec_round_ema = float(n)
        else:
            self._spec_round_ema += SPEC_EMA_ALPHA * (
                n - self._spec_round_ema)

    def _est_round_tokens(self) -> float:
        """Expected delivered tokens per decode round: exactly 1 in plain
        mode; in spec mode an EMA of tokens-per-verify-round (optimistic
        per_tok before any observation — worst case that costs is one
        lost overlap boundary, never junk). An EMA, not the engine-
        lifetime average (ADVICE r5 #2): after a workload shift from
        high- to low-acceptance text the stale lifetime average
        undersized chunks and triggered premature drains."""
        if not self.spec:
            return 1.0
        if self._spec_round_ema is None:
            return float(self.spec + 1)
        return min(float(self.spec + 1), max(1.0, self._spec_round_ema))

    def _drain_pending(self) -> None:
        """Fetch + replay the in-flight decode chunk, if any. Must run
        before any prefill dispatch or idle return: replay frees slots and
        completes requests, and the host bookkeeping must be current
        before slot assignments change."""
        p = self._pending
        if p is not None:
            self._pending = None
            self._replay(p)

    def _replay(self, pending) -> None:
        """Fetch one dispatched chunk's packed rows and replay them into
        per-request results. `slot_req` is the slot->request map AT
        DISPATCH time; a slot freed since (cancellation applied at a chunk
        boundary while this chunk was in flight) no longer maps to its
        captured request and is skipped — its rows are junk by contract,
        exactly like post-EOS surplus."""
        slot_req, steps, out, rows_added, kd = pending
        t_replay = time.perf_counter()
        out_np = np.asarray(out)   # one fetch per chunk
        # in-flight rows for THIS chunk are now accounted by the replay's
        # own host_lengths advancement (junk/surplus rows stay counted in
        # neither — the next prefill into the slot resets both)
        alive = [self.scheduler.slot_request(s) == slot_req[s]
                 for s in range(self.n_slots)]
        done_slots: set[int] = set()
        if self.spec:
            kp1 = kd + 1
            oc = self._out_cols
            for s in range(steps):
                for slot, req in enumerate(slot_req):
                    if req < 0 or slot in done_slots or not alive[slot]:
                        continue
                    cnt = int(out_np[s, slot, 0])
                    emits = out_np[s, slot, 1:].reshape(kp1, oc)
                    self._spec_verifies += 1
                    # live acceptance estimators: the round delivered cnt
                    # tokens = (cnt - 1) accepted drafts + the bonus
                    self._observe_round_tokens(cnt)
                    if self._spec_adapt is not None:
                        self._spec_adapt.observe(slot, cnt - 1, kd)
                    for j in range(cnt):
                        self._host_lengths[slot] += 1
                        # count DELIVERED tokens, not the round's emit
                        # count: a mid-round finish drops the surplus, and
                        # the tokens-per-round metric must not claim them
                        self._spec_tokens += 1
                        tok, lp, top = self._unpack_out(emits[j])
                        if self._record_token(req, slot, tok, lp, top):
                            done_slots.add(slot)
                            break
        else:
            for row in out_np:   # [steps, n_slots, out_cols]
                for slot, req in enumerate(slot_req):
                    if req < 0 or slot in done_slots or not alive[slot]:
                        continue
                    self._host_lengths[slot] += 1
                    tok, lp, top = self._unpack_out(row[slot])
                    if self._record_token(req, slot, tok, lp, top):
                        # finished mid-chunk: later tokens are garbage for
                        # this slot; drop them (its cache is reset by the
                        # next prefill into the slot). The local return
                        # value — not the shared _done set — decides, so a
                        # concurrent release() from a server thread can't
                        # unfinish it.
                        done_slots.add(slot)
        # remove THIS chunk's planned rows: delivered ones re-entered via
        # host_lengths above; junk rows belong to freed slots whose state
        # the next prefill resets anyway
        self._inflight = np.maximum(self._inflight - rows_added, 0)
        self._perf["fetch_replay_s"] += time.perf_counter() - t_replay

    def _record_token(self, req_id: int, slot: int, token: int,
                      lp: float = 0.0, top: dict[int, float] | None = None,
                      first_token: bool = False) -> bool:
        """Returns True when this token finished the request."""
        if first_token:
            now = time.monotonic()
            self._first_token_t[req_id] = now
            self._ttft_window.append(now - self._submit_t[req_id])
            if req_id in self._req_trace:
                self._decode_mark[req_id] = self._decode_agg.snapshot()
        res = self._results[req_id]
        res.append(token)
        self._logprobs[req_id].append(lp)
        if top is not None and req_id in self._toplogprobs:
            self._toplogprobs[req_id].append(top)
        hit_eos = self.eos_id is not None and token == self.eos_id
        # stop-sequence suffix match (host-side, at chunk-boundary replay):
        # the matched sequence is EXCLUDED from the result (OpenAI
        # semantics) — matching over the accumulated output makes
        # sequences spanning chunk boundaries work for free
        hit_stop = 0
        if not hit_eos:
            for ss in self._req_stop.get(req_id, ()):
                if len(res) >= len(ss) and res[-len(ss):] == ss:
                    hit_stop = len(ss)
                    break
        if hit_stop:
            del res[-hit_stop:]
            del self._logprobs[req_id][-hit_stop:]
            if req_id in self._toplogprobs:
                del self._toplogprobs[req_id][-hit_stop:]
        # cache exhaustion: _host_lengths == KV rows written; the NEXT decode
        # writes at that index, which must stay < max_len (the host mirror
        # avoids a device fetch here)
        out_of_room = self._host_lengths[slot] >= self.max_len
        freed = self.scheduler.token_done(
            slot, finished=hit_eos or bool(hit_stop) or out_of_room)
        if freed:
            # OpenAI finish_reason semantics: "stop" = the model chose to
            # end (EOS) or a stop sequence matched; "length" = budget/cache
            # truncation
            self._finish_reasons[req_id] = (
                "stop" if (hit_eos or hit_stop) else "length")
            self._finish_t[req_id] = time.monotonic()
            self._done.add(req_id)
            self._prompts.pop(req_id, None)
            self._max_new.pop(req_id, None)
            self._req_samp.pop(req_id, None)
            self._req_stop.pop(req_id, None)
            self._req_aids.pop(req_id, None)
            self._deadlines.pop(req_id, None)
            self._obs_finish(req_id)
        return freed

    def _obs_finish(self, req_id: int) -> None:
        """Per-request telemetry, emitted ONCE at finish (never inside
        the decode loop): lifecycle counter, TTFT/TPOT/queue-wait
        histogram observations, and — when the request carried a SAMPLED
        trace id — the retrospective queue/prefill/decode spans
        reconstructed from the timestamps the engine already keeps for
        request_timing()."""
        reason = self._finish_reasons.get(req_id, "length")
        obs_metrics.REQUESTS.inc(component=self.role, event=reason)
        sub = self._submit_t.get(req_id)
        pstart = self._prefill_start_t.get(req_id)
        first = self._first_token_t.get(req_id)
        fin = self._finish_t.get(req_id)
        n_tok = len(self._results.get(req_id, ()))
        if sub is not None and first is not None:
            obs_metrics.TTFT_SECONDS.observe(first - sub,
                                             component=self.role)
        if sub is not None and pstart is not None:
            obs_metrics.QUEUE_WAIT_SECONDS.observe(pstart - sub,
                                                   component=self.role)
        if first is not None and fin is not None and n_tok >= 2:
            obs_metrics.TPOT_SECONDS.observe((fin - first) / (n_tok - 1),
                                             component=self.role)
        trace = self._req_trace.pop(req_id, None)
        mark = self._decode_mark.pop(req_id, None)
        if trace is None or not TRACER.sampled(trace):
            return
        tenant = self._req_tenant.get(req_id)
        TRACER.record_span(f"{self.role}.queue", "queue", trace, sub,
                           pstart, tenant=tenant)
        TRACER.record_span(f"{self.role}.prefill", "prefill", trace,
                           pstart, first,
                           prompt_len=self._req_plen.get(req_id),
                           cached_prefix_len=self._cached_prefix.get(
                               req_id, 0))
        attrs: dict[str, Any] = {"n_tokens": n_tok,
                                 "finish_reason": reason,
                                 "tenant": tenant}
        if mark is not None:
            attrs.update(StepAggregator.window(
                mark, self._decode_agg.snapshot()))
        TRACER.record_span(f"{self.role}.decode", "decode", trace,
                           first, fin, **attrs)


# -- disaggregated serving roles (ISSUE 13, ROADMAP #3) -----------------------
#
# Prefill and decode want opposite things from one engine: prefill is a
# bursty, compute-bound batch job whose chained dispatches block the step
# loop for a whole chunk plan, while decode wants short, uniform steps —
# interleaving them is exactly the interference the loadgen per-bucket
# TTFT table measures (a 4k-token prompt arriving mid-window spikes every
# active request's TPOT). The disaggregated configuration
# (serving/disagg.py) splits the two onto dedicated engine ROLES and moves
# the finished KV between them as radix-cache block payloads — the r10
# handoff currency. Both roles are ordinary LLMEngines (one program menu,
# one scheduler, one parity story); the role classes below only pin the
# contract each side of the split relies on. Like LLMEngine itself, role
# engines may only be constructed inside supervisor factory functions
# (scripts/check_dataplane.py lints all three names).


class PrefillEngine(LLMEngine):
    """Dedicated prefill worker: runs (chunked) prefill — starting from
    the longest chain its own radix prefix cache already holds — and
    STOPS at KV materialization. Every submission is clamped to ONE
    greedy token, which the scheduler counts as the request's completion
    AT the prefill, so the step loop never dispatches a decode program
    and a queued long prompt never steals a decode step from anyone.
    The single sampled token is a byproduct the coordinator discards
    (greedy, so a crash-replay of an un-handed-off prefill is
    byte-deterministic); the PRODUCT is the banked block-aligned prefix
    KV in self.kvcache, which the coordinator matches and hands to the
    decode worker through a KVHandoff (serving/disagg.py)."""

    role = "prefill"

    def __init__(self, params, cfg, **kw):
        # the radix cache IS the handoff staging area — a prefill worker
        # without it would materialize KV with no way to export it
        kw["prefix_cache"] = True
        super().__init__(params, cfg, **kw)

    def submit(self, prompt, max_new_tokens: int = 1,
               temperature: float = 0.0, **kw) -> int:
        # max_new/temperature are clamped, not honored: KV
        # materialization is the entire job, and greedy keeps the
        # supervisor's journal-replay byte-exact
        return super().submit(prompt, 1, 0.0, **kw)


class DecodeEngine(LLMEngine):
    """Dedicated decode worker: admissions are EXPECTED to find their
    block-aligned prompt prefix already in the radix cache (a KVHandoff
    inserted it), so per-request prefill compute is at most one tail
    bucket of continuation — decode steps stay short and uniform. A
    full/chunked prefill here means the handoff was missed (an eviction
    raced the insert, or a supervisor replay landed on a fresh post-crash
    cache): counted in `full_prefills`, never fatal — the decode worker
    degrades to colocated behavior rather than refusing the request,
    which is what keeps the crash-recovery story identical to r11's."""

    role = "decode"

    def __init__(self, params, cfg, **kw):
        kw["prefix_cache"] = True
        super().__init__(params, cfg, **kw)
        # admissions (>= 1 block of prompt) that found NO cached prefix
        # and paid a full prefill — the disagg miss counter
        self.full_prefills = 0

    def _prefix_lookup(self, action):
        hit = super()._prefix_lookup(action)
        if hit is None and self.prefix_block_tokens \
                and len(self._prompts.get(action.req_id, ())) - 1 \
                >= self.prefix_block_tokens:
            self.full_prefills += 1
        return hit

    def metrics(self):
        out = super().metrics()
        out["disagg_full_prefills"] = self.full_prefills
        return out
