"""Continuous-batching LLM engine — the KServe/Triton-GPU serving runtime
replaced by a TPU-native design (SURVEY.md §2.6, BASELINE config #5: the
Llama InferenceService TTFT metric runs through this engine).

Split into the two halves the hardware wants:

  - **Scheduling** (C++ core, serving/scheduler.py): request queue, decode
    slots, prefill-bucket choice. Decisions only — never touches tensors.
  - **Execution** (this module): a fixed menu of compiled XLA programs —
    one prefill program per bucket length plus ONE decode program over all
    slots — so serving never recompiles. Static shapes are the TPU
    constraint the whole design bends around: variable prompts are padded
    up to a bucket; the decode batch always runs full-width with inactive
    slots masked by the engine.

Prefill priority keeps TTFT low; decode always re-batches every step
(continuous batching), so finished slots refill immediately from the queue.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models import llama
from kubeflow_tpu.serving.scheduler import (DecodeAction, PrefillAction,
                                            make_scheduler)


class LLMEngine:
    """Greedy continuous-batching generation over llama-family params."""

    def __init__(self, params, cfg: llama.LlamaConfig, *, n_slots: int = 4,
                 max_len: int = 512, buckets: Sequence[int] = (64, 128, 256),
                 max_queue: int = 1024, eos_id: int | None = None,
                 prefer_native: bool = True, decode_chunk: int = 8):
        if max(buckets) >= max_len:
            raise ValueError("largest bucket must leave room to decode")
        self.params = params
        self.cfg = cfg
        self.n_slots = n_slots
        self.max_len = max_len
        self.buckets = tuple(sorted(buckets))
        self.eos_id = eos_id
        self.scheduler = make_scheduler(n_slots, self.buckets, max_queue,
                                        prefer_native=prefer_native)
        self.cache = llama.init_cache(cfg, n_slots, max_len)
        self.lengths = jnp.zeros((n_slots,), jnp.int32)
        self.last_tokens = jnp.zeros((n_slots,), jnp.int32)
        self._host_lengths = np.zeros((n_slots,), np.int64)
        self.decode_chunk = max(1, decode_chunk)
        self._max_new: dict[int, int] = {}

        self._prompts: dict[int, list[int]] = {}
        self._results: dict[int, list[int]] = {}
        self._submit_t: dict[int, float] = {}
        self._first_token_t: dict[int, float] = {}
        self._done: set[int] = set()
        self._ttft_window: collections.deque[float] = collections.deque(
            maxlen=1024)
        # Guards submit vs. the engine-loop thread: held across
        # scheduler.submit + request-dict population so scheduler.next()
        # (also taken under it) can never hand out a prefill whose request
        # dicts aren't populated yet.
        self._submit_lock = threading.Lock()
        self._prefill_fns: dict[int, Any] = {}
        self._decode_fn = jax.jit(self._decode, donate_argnums=(1, 2, 3))

    # -- compiled programs ---------------------------------------------------
    # params are an explicit argument, never a closure: a closed-over pytree
    # would be inlined into the HLO as constants (hundreds of MB shipped to
    # the compiler and frozen into the executable). All slot state (cache,
    # lengths, last_tokens) lives on device and is updated inside the jitted
    # programs — the host loop does exactly ONE device->host fetch per
    # iteration (the new tokens), which is what keeps per-step latency at
    # dispatch cost instead of several tunnel round-trips.

    def _prefill(self, params, cache, lengths, last_tokens, tokens, slot,
                 prompt_len):
        """tokens [1, bucket] right-padded; writes KV into `slot`."""
        logits, ks, vs = llama.prefill(params, tokens, self.cfg)
        bucket = tokens.shape[1]
        k = cache["k"].at[:, slot, :bucket].set(ks[:, 0])
        v = cache["v"].at[:, slot, :bucket].set(vs[:, 0])
        last = jax.lax.dynamic_index_in_dim(logits[0], prompt_len - 1,
                                            keepdims=False)
        tok = jnp.argmax(last, -1).astype(jnp.int32)
        return ({"k": k, "v": v}, lengths.at[slot].set(prompt_len),
                last_tokens.at[slot].set(tok), tok)

    def _decode(self, params, cache, lengths, last_tokens, active):
        logits, cache = llama.decode_step(params, last_tokens, cache,
                                          lengths, self.cfg)
        toks = jnp.argmax(logits, -1).astype(jnp.int32)
        lengths = lengths + active.astype(jnp.int32)
        last_tokens = jnp.where(active, toks, last_tokens)
        return cache, lengths, last_tokens, toks

    def _prefill_fn(self, bucket: int):
        if bucket not in self._prefill_fns:
            self._prefill_fns[bucket] = jax.jit(
                self._prefill, donate_argnums=(1, 2, 3))
        return self._prefill_fns[bucket]

    # -- public API ----------------------------------------------------------

    def submit(self, prompt: Sequence[int], max_new_tokens: int = 32) -> int:
        with self._submit_lock:
            req_id = self.scheduler.submit(len(prompt), max_new_tokens,
                                           time.monotonic())
            self._prompts[req_id] = list(prompt)
            self._results[req_id] = []
            self._max_new[req_id] = max_new_tokens
            self._submit_t[req_id] = time.monotonic()
        return req_id

    def step(self) -> bool:
        """One engine iteration: a prefill wave or a batched decode.
        False = idle.

        All queued prefills dispatch back-to-back BEFORE any token fetch:
        jax's async dispatch overlaps prefill k+1's compute with prefill
        k's device->host round-trip, so a burst of n arrivals pays ~one
        RTT instead of n (the same chaining trick as _do_decode)."""
        with self._submit_lock:
            action = self.scheduler.next()
        if action is None:
            return False
        if isinstance(action, DecodeAction):
            self._do_decode()
            return True
        actions = [action]
        while len(actions) < self.n_slots:
            with self._submit_lock:
                nxt = self.scheduler.next()
            if not isinstance(nxt, PrefillAction):
                break   # Decode/None: dropping is safe — the decode pass
                        # re-derives from slot state on the next step()
            actions.append(nxt)
        dispatched = [(a, self._dispatch_prefill(a)) for a in actions]
        for a, tok in dispatched:
            self._host_lengths[a.slot] = a.prompt_len
            self._record_token(a.req_id, a.slot, int(tok), first_token=True)
        return True

    def run_until_idle(self) -> None:
        while self.step():
            pass

    def is_done(self, req_id: int) -> bool:
        return req_id in self._done

    def result(self, req_id: int) -> list[int]:
        if req_id not in self._done:
            raise KeyError(f"request {req_id} not finished")
        return self._results[req_id]

    def release(self, req_id: int) -> None:
        """Drop all per-request state. Long-lived servers MUST call this
        after reading result(), or per-request dicts grow without bound."""
        self._done.discard(req_id)
        self._results.pop(req_id, None)
        self._submit_t.pop(req_id, None)
        self._first_token_t.pop(req_id, None)

    def generate(self, prompt: Sequence[int],
                 max_new_tokens: int = 32) -> list[int]:
        rid = self.submit(prompt, max_new_tokens)
        while not self.is_done(rid):
            if not self.step():
                raise RuntimeError("engine idle with request outstanding")
        return self.result(rid)

    def ttft_seconds(self, req_id: int) -> float | None:
        """Submit→first-token latency for one request (None until then)."""
        if req_id not in self._first_token_t:
            return None
        return self._first_token_t[req_id] - self._submit_t[req_id]

    def metrics(self) -> dict[str, Any]:
        ttfts = list(self._ttft_window)  # survives release() of old requests
        s = self.scheduler.stats()
        out = {"queued": s.queued, "active": s.active,
               "completed": s.completed, "rejected": s.rejected}
        if ttfts:
            out["ttft_p50_s"] = float(np.percentile(ttfts, 50))
            out["ttft_p99_s"] = float(np.percentile(ttfts, 99))
        return out

    # -- internals -----------------------------------------------------------

    def _dispatch_prefill(self, a: PrefillAction):
        """Dispatch one prefill; returns the (device) next-token array
        WITHOUT fetching, so callers can pipeline several prefills."""
        prompt = self._prompts[a.req_id]
        tokens = np.zeros((1, a.bucket_len), np.int32)
        tokens[0, :len(prompt)] = prompt
        self.cache, self.lengths, self.last_tokens, next_tok = \
            self._prefill_fn(a.bucket_len)(
                self.params, self.cache, self.lengths, self.last_tokens,
                jnp.asarray(tokens), a.slot, a.prompt_len)
        return next_tok

    def _do_decode(self) -> None:
        """Chained decode: dispatch K steps back-to-back WITHOUT fetching
        between them (device state is self-contained), then drain the K
        token arrays. JAX's async dispatch overlaps the host<->device
        round-trip with device compute — on a tunneled/remote device this
        is the difference between RTT-bound and compute-bound decode.

        K = min remaining tokens across active slots (no overrun), capped
        by cache headroom and a scheduling-latency bound: new arrivals wait
        at most K steps for their prefill."""
        slot_req = [self.scheduler.slot_request(s) for s in range(self.n_slots)]
        active = np.array([r >= 0 for r in slot_req], bool)
        remaining = [self._max_new[r] - len(self._results[r])
                     for r in slot_req if r >= 0]
        # k chained steps write KV rows L..L+k-1 for the fullest slot, so
        # k <= max_len - L keeps every write in bounds
        headroom = self.max_len - int(
            max(self._host_lengths[s] for s in range(self.n_slots)
                if active[s]))
        k = max(1, min(min(remaining), headroom, self.decode_chunk))
        active_dev = jnp.asarray(active)

        tok_batches = []
        for _ in range(k):
            self.cache, self.lengths, self.last_tokens, toks = \
                self._decode_fn(self.params, self.cache, self.lengths,
                                self.last_tokens, active_dev)
            tok_batches.append(toks)
        done_slots: set[int] = set()
        for toks in tok_batches:
            toks_np = np.asarray(toks)  # first fetch blocks; rest are ready
            for slot, req in enumerate(slot_req):
                if req < 0 or slot in done_slots:
                    continue
                self._host_lengths[slot] += 1
                if self._record_token(req, slot, int(toks_np[slot])):
                    # finished mid-chain: later chained tokens are garbage
                    # for this slot; drop them (its cache is reset by the
                    # next prefill into the slot). The local return value —
                    # not the shared _done set — decides, so a concurrent
                    # release() from a server thread can't unfinish it.
                    done_slots.add(slot)

    def _record_token(self, req_id: int, slot: int, token: int,
                      first_token: bool = False) -> bool:
        """Returns True when this token finished the request."""
        if first_token:
            now = time.monotonic()
            self._first_token_t[req_id] = now
            self._ttft_window.append(now - self._submit_t[req_id])
        self._results[req_id].append(token)
        hit_eos = self.eos_id is not None and token == self.eos_id
        # cache exhaustion: _host_lengths == KV rows written; the NEXT decode
        # writes at that index, which must stay < max_len (the host mirror
        # avoids a device fetch here)
        out_of_room = self._host_lengths[slot] >= self.max_len
        freed = self.scheduler.token_done(slot, finished=hit_eos or out_of_room)
        if freed:
            self._done.add(req_id)
            self._prompts.pop(req_id, None)
            self._max_new.pop(req_id, None)
        return freed
