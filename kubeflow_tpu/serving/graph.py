"""InferenceGraph — the kserve inference-graph analog (SURVEY.md §2.4;
⊘ kserve `pkg/apis/serving/v1alpha1/inference_graph_types.go` +
`pkg/router/main.go`).

kserve's InferenceGraph CRD composes InferenceServices into a routing
graph served by a dedicated router Deployment. Four node types, same
semantics here:

    Sequence — run steps in order; each step receives the original request
               (`data: $request`) or the previous step's response
               (`data: $response`, the default for non-first steps).
    Switch   — route to the FIRST step whose `condition` matches the
               request body; 404 if none match.
    Ensemble — fan out to all steps in parallel, merge full responses as
               {stepName: response} (e.g. {"a": {"predictions": [...]}}).
    Splitter — pick exactly one step by `weight` (deterministic modular
               schedule like the canary Router — no RNG flakes in tests).

Spec (kserve shape):

    kind: InferenceGraph
    spec:
      nodes:
        root:                               # execution starts at "root"
          routerType: Sequence
          steps:
            - name: step-1
              serviceName: my-isvc          # leaf: an InferenceService
              data: $request
              dependency: Hard              # Hard fails the graph; Soft skips
            - name: step-2
              nodeName: other-node          # or recurse into another node
              condition: instances.0.kind == "x"   # Switch only
              weight: 60                    # Splitter only

Conditions are a GJSON-lite dotted path into the request JSON, with an
optional `== <json literal>` comparison (bare path = truthy existence).

The controller materializes one GraphRouter HTTP server per graph (the
router-Deployment analog); leaf steps POST to the member InferenceService's
v1 dataplane. Chained `$response` data converts `{"predictions": P}` into
`{"instances": P}` so the v1 contract holds along the chain.
"""

from __future__ import annotations

import http.client
import json
import threading
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

from kubeflow_tpu.control.conditions import (JobConditionType, set_condition)
from kubeflow_tpu.control.controller import Controller
from kubeflow_tpu.pipelines.artifacts import json_digest
from kubeflow_tpu.serving.controller import ISVC_KIND

GRAPH_KIND = "InferenceGraph"
ROUTER_TYPES = ("Sequence", "Switch", "Ensemble", "Splitter")


def validate_graph(graph: dict[str, Any]) -> list[str]:
    errs: list[str] = []
    nodes = graph.get("spec", {}).get("nodes")
    if not isinstance(nodes, dict) or not nodes:
        return ["spec.nodes must be a non-empty mapping"]
    if "root" not in nodes:
        errs.append('spec.nodes must contain a "root" node')
    for node_name, node in nodes.items():
        rt = node.get("routerType")
        if rt not in ROUTER_TYPES:
            errs.append(f"nodes.{node_name}.routerType invalid: {rt!r} "
                        f"(one of {ROUTER_TYPES})")
        steps = node.get("steps")
        if not isinstance(steps, list) or not steps:
            errs.append(f"nodes.{node_name}.steps must be a non-empty list")
            continue
        names = [s.get("name") for s in steps
                 if isinstance(s, dict) and s.get("name")]
        for dup in sorted({n for n in names if names.count(n) > 1}):
            # Ensemble responses merge by step name; a duplicate would
            # silently shadow its sibling's response
            errs.append(f"nodes.{node_name}: duplicate step name {dup!r}")
        for i, step in enumerate(steps):
            where = f"nodes.{node_name}.steps[{i}]"
            if not isinstance(step, dict):
                errs.append(f"{where} must be a mapping")
                continue
            has_svc = bool(step.get("serviceName"))
            has_node = bool(step.get("nodeName"))
            if has_svc == has_node:
                errs.append(f"{where}: exactly one of serviceName|nodeName")
            if has_node and step["nodeName"] not in nodes:
                errs.append(f"{where}: unknown nodeName "
                            f"{step['nodeName']!r}")
            if step.get("data") not in (None, "$request", "$response"):
                errs.append(f"{where}.data must be $request or $response")
            if step.get("dependency", "Hard") not in ("Hard", "Soft"):
                errs.append(f"{where}.dependency must be Hard or Soft")
            if rt == "Splitter" and (
                    not isinstance(step.get("weight"), int)
                    or step.get("weight", 0) <= 0):
                errs.append(f"{where}: Splitter steps need a positive "
                            "int weight")
            if rt == "Switch" and i < len(steps) - 1 \
                    and not step.get("condition"):
                # a condition-less step matches everything; only the last
                # step may omit it (the default branch)
                errs.append(f"{where}: non-final Switch steps need a "
                            "condition")
    # cycle check: recursing into an ancestor node would loop forever.
    # `safe` memoizes nodes proven cycle-free so diamond-shaped DAGs stay
    # linear instead of enumerating every root-to-leaf path
    safe: set[str] = set()

    def walk(name: str, stack: tuple[str, ...]) -> None:
        if name in safe:
            return
        if name in stack:
            errs.append("node cycle: " + " -> ".join(stack + (name,)))
            return
        for step in nodes.get(name, {}).get("steps") or ():
            if isinstance(step, dict) and step.get("nodeName"):
                walk(step["nodeName"], stack + (name,))
        safe.add(name)

    if not errs:
        walk("root", ())
    return errs


def _json_path(obj: Any, path: str) -> Any:
    """GJSON-lite: dotted path, integer segments index into lists."""
    cur = obj
    for seg in path.split("."):
        if isinstance(cur, list):
            try:
                cur = cur[int(seg)]
            except (ValueError, IndexError):
                return None
        elif isinstance(cur, dict):
            cur = cur.get(seg)
        else:
            return None
    return cur


def eval_condition(cond: str, body: Any) -> bool:
    """`path == <json literal>` comparison, or bare-path truthiness."""
    if "==" in cond:
        path, _, lit = cond.partition("==")
        try:
            want = json.loads(lit.strip())
        except json.JSONDecodeError:
            want = lit.strip()
        return _json_path(body, path.strip()) == want
    return bool(_json_path(body, cond.strip()))


class GraphExecutionError(Exception):
    def __init__(self, status: int, message: str):
        self.status = status
        super().__init__(message)


class GraphRouter:
    """HTTP server executing one InferenceGraph — the kserve router
    Deployment analog. `resolve` maps serviceName → base URL (looked up
    live, so member ISVC rollouts/reschedules are picked up per request)."""

    def __init__(self, name: str, nodes: dict[str, Any], resolve,
                 port: int = 0):
        self.name = name
        self.nodes = nodes
        self.resolve = resolve
        self._splitter_count: dict[str, int] = {}
        self._lock = threading.Lock()
        router = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                try:
                    body = json.loads(self.rfile.read(length) or b"{}")
                    result = router.execute("root", body)
                    code, payload = 200, result
                except GraphExecutionError as e:
                    code, payload = e.status, {"error": str(e)}
                except Exception as e:  # defensive: malformed JSON etc.
                    code, payload = 400, {"error": f"{type(e).__name__}: {e}"}
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever, daemon=True,
                         name=f"graph-{name}").start()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    # -- execution ------------------------------------------------------------

    def execute(self, node_name: str, request: Any) -> Any:
        node = self.nodes[node_name]
        rt = node["routerType"]
        steps = node["steps"]
        if rt == "Sequence":
            return self._run_sequence(steps, request)
        if rt == "Switch":
            for step in steps:
                cond = step.get("condition")
                if cond is None or eval_condition(cond, request):
                    out = self._try_step(step, request)
                    if out is not None:
                        return out
                    # Soft failure: fall through to the next matching branch
            raise GraphExecutionError(404, "no Switch condition matched")
        if rt == "Ensemble":
            # one thread per step and per request (not a shared bounded
            # pool: nested Ensemble nodes executing inside pool workers
            # would deadlock waiting on children that can never schedule)
            with ThreadPoolExecutor(
                    max_workers=len(steps),
                    thread_name_prefix=f"graph-{self.name}") as pool:
                futures = {step.get("name", f"step-{i}"):
                           pool.submit(self._try_step, step, request)
                           for i, step in enumerate(steps)}
                merged: dict[str, Any] = {}
                for sname, fut in futures.items():
                    out = fut.result()
                    if out is not None:
                        merged[sname] = out
            if not merged:
                raise GraphExecutionError(502, "all Ensemble steps failed")
            return merged
        # Splitter
        total = sum(s["weight"] for s in steps)
        with self._lock:
            n = self._splitter_count[node_name] = (
                self._splitter_count.get(node_name, 0) + 1)
        # deterministic weighted schedule: request n maps to point
        # (n * 7919) mod total; the prime stride walks every residue class
        # so each cumulative-weight bucket receives exactly its share
        point = (n * 7919) % total
        acc = 0
        for step in steps:
            acc += step["weight"]
            if point < acc:
                return self._run_step(step, request)
        return self._run_step(steps[-1], request)

    def _run_sequence(self, steps: list[dict], request: Any) -> Any:
        original, current = request, request
        for i, step in enumerate(steps):
            data = step.get("data") or ("$request" if i == 0
                                        else "$response")
            payload = original if data == "$request" else current
            if data == "$response" and isinstance(payload, dict) \
                    and "predictions" in payload:
                # keep the v1 contract along the chain: the previous hop's
                # predictions become this hop's instances
                payload = {"instances": payload["predictions"]}
            out = self._try_step(step, payload)
            if out is not None:
                current = out
        return current

    def _try_step(self, step: dict, payload: Any) -> Any:
        """Run one step honoring its dependency mode: Hard failures
        propagate; Soft failures return None (caller keeps going)."""
        try:
            return self._run_step(step, payload)
        except GraphExecutionError:
            if step.get("dependency", "Hard") == "Hard":
                raise
            return None

    def _run_step(self, step: dict, payload: Any) -> Any:
        if step.get("nodeName"):
            return self.execute(step["nodeName"], payload)
        svc = step["serviceName"]
        url = self.resolve(svc)
        if url is None:
            raise GraphExecutionError(
                503, f"InferenceService {svc!r} is not ready")
        host, port = url.replace("http://", "").split(":")
        try:
            conn = http.client.HTTPConnection(host, int(port), timeout=60)
            conn.request("POST", f"/v1/models/{svc}:predict",
                         body=json.dumps(payload),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = resp.read()
            conn.close()
        except OSError as e:
            raise GraphExecutionError(502, f"{svc}: unreachable: {e}") \
                from None
        if resp.status != 200:
            raise GraphExecutionError(
                resp.status, f"{svc}: {data.decode(errors='replace')}")
        return json.loads(data)


class InferenceGraphController(Controller):
    """Reconciles InferenceGraph → one GraphRouter, resolving member
    InferenceServices through the store (⊘ kserve
    `pkg/controller/v1alpha1/inferencegraph/controller.go`)."""

    kind = GRAPH_KIND
    resync_period = 2.0

    def __init__(self, cluster):
        super().__init__(cluster)
        self._lock = threading.RLock()
        self._routers: dict[tuple[str, str], tuple[str, GraphRouter]] = {}

    def stop(self) -> None:
        super().stop()
        with self._lock:
            for _, router in self._routers.values():
                router.stop()
            self._routers.clear()

    def reconcile_deleted(self, name: str, namespace: str) -> float | None:
        with self._lock:
            entry = self._routers.pop((namespace, name), None)
        if entry is not None:
            entry[1].stop()
        return None

    def reconcile(self, graph: dict[str, Any]) -> float | None:
        name = graph["metadata"]["name"]
        ns = graph["metadata"].get("namespace", "default")
        errs = validate_graph(graph)
        if errs:
            def fail(o):
                # an edited-to-invalid spec must not keep advertising Ready
                o["status"]["conditions"] = [
                    c for c in o["status"].get("conditions", ())
                    if c["type"] != "Ready"]
                set_condition(o["status"], JobConditionType.FAILED,
                              "InvalidSpec", "; ".join(errs))
            self.store.mutate(GRAPH_KIND, name, fail, ns)
            return None
        nodes = graph["spec"]["nodes"]
        revision = json_digest(nodes)[:12]

        def resolve(svc: str) -> str | None:
            isvc = self.store.try_get(ISVC_KIND, svc, ns)
            if isvc is None:
                return None
            return isvc.get("status", {}).get("url")

        with self._lock:
            entry = self._routers.get((ns, name))
            if entry is not None and entry[0] != revision:
                entry[1].stop()   # spec changed: replace the router
                entry = None
            if entry is None:
                entry = (revision, GraphRouter(f"{ns}/{name}", nodes,
                                               resolve))
                self._routers[(ns, name)] = entry
            else:
                entry[1].nodes = nodes
        router = entry[1]

        members = sorted({s["serviceName"]
                          for node in nodes.values()
                          for s in node["steps"] if s.get("serviceName")})
        missing = [m for m in members if resolve(m) is None]

        def write(o):
            o["status"]["url"] = router.url
            o["status"]["members"] = members
            o["status"]["pendingMembers"] = missing
            # a fixed spec must shed the stale Failed from its invalid past
            drop = ("Ready", JobConditionType.FAILED) if missing \
                else (JobConditionType.FAILED,)
            o["status"]["conditions"] = [
                c for c in o["status"].get("conditions", ())
                if c["type"] not in drop]
            if not missing:
                set_condition(o["status"], "Ready", "RouterReady",
                              "graph router is ready")
        self.store.mutate(GRAPH_KIND, name, write, ns)
        return 2.0 if missing else None
