"""Serving contract proof: Llama-3-8B InferenceService on a v5e slice.

BASELINE config #5 is "InferenceService: Llama-3-8B"; no 8-chip slice exists
on a dev box, so — exactly like training/contract.py for config #3 — the
contract is proven against the REAL v5e compiler via PJRT topology AOT:

  - Build the engine's program menu (batched prefill wave + chained decode
    chunk — the same unbound methods LLMEngine compiles at runtime) at the
    true 8B dimensions, with params sharded by the model's logical axes and
    the KV cache sharded over kv-heads on a tensor=8 mesh.
  - AOT-compile each program for the v5e target and read XLA's buffer
    assignment: compile() itself enforces the HBM budget (RESOURCE_EXHAUSTED
    on an oversubscribed layout), and memory_analysis() reports the heap
    peak per device.
  - Account weights + KV cache residency analytically from the shardings.

Variants: weights as bf16 and weight-only int8 (ops/quant per-channel — the
production decode configuration).

Reference anchor (SURVEY.md §2.4 KServe + §2.6 Triton-class runtime row):
the reference serves 8B-class LLMs through kserve runtimes on GPU pools;
here the same contract is a mesh + logical-axis rules on the engine's
static program menu.
"""

from __future__ import annotations

import functools
import math
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from kubeflow_tpu.models import llama
from kubeflow_tpu.serving.llm import LLMEngine

V5E_HBM_BYTES = 16 * 1024**3


class _AbstractEngine:
    """Just enough instance surface to trace LLMEngine's program methods.
    The attributes reference the SAME unbound functions the live engine
    jits — the proof covers the production code path, not a re-derivation."""

    _prefill = LLMEngine._prefill
    _prefill_cont = LLMEngine._prefill_cont
    _unpack_wave = LLMEngine._unpack_wave
    _extract_prefix = LLMEngine._extract_prefix
    _decode = LLMEngine._decode
    _cache_write = LLMEngine._cache_write
    _sample_last = staticmethod(LLMEngine._sample_last)
    _pick = staticmethod(LLMEngine._pick)

    def __init__(self, cfg: llama.LlamaConfig, kv_quantize: str | None = None):
        self.cfg = cfg
        self.kv_quantize = kv_quantize
        # the proof covers the non-speculative, single-adapter menu (spec
        # mode swaps the decode program for _spec_decode and adapters add
        # a rank-r bypass — both ride within the margin)
        self.spec = None
        self.adapters = None
        self._row_extra = 3


def _abstract_tree(tree, shardings):
    return jax.tree.map(
        lambda l, sh: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=sh),
        tree, shardings)


def _leaf_device_bytes(leaf) -> int:
    shard = leaf.sharding.shard_shape(leaf.shape)
    return math.prod(shard) * leaf.dtype.itemsize


def _peak(compiled) -> int:
    ma = compiled.memory_analysis()
    if ma is None:
        return 0
    peak = getattr(ma, "peak_memory_in_bytes", 0) or (
        ma.argument_size_in_bytes + ma.temp_size_in_bytes)
    return int(peak)


def aot_serving_report(
    topology: str | None = "v5e:2x4",
    *,
    quantize: str | None = None,
    kv_quantize: str | None = None,
    n_devices: int = 8,
    n_slots: int = 8,
    max_len: int = 8192,
    bucket: int = 2048,
    width: int = 4,
    decode_steps: int = 8,
    do_compile: bool = True,
    model_overrides: dict[str, Any] | None = None,
) -> dict[str, Any]:
    """Compile the engine's 8B program menu for a v5e target; return the
    memory evidence. `topology=None` targets `n_devices` local devices
    instead (the CI virtual-CPU path)."""
    from kubeflow_tpu.parallel import MeshConfig
    from kubeflow_tpu.parallel.mesh import make_mesh
    from kubeflow_tpu.parallel.sharding import tree_logical_to_sharding

    if topology is not None:
        from jax.experimental import topologies

        devices = list(topologies.get_topology_desc(topology).devices)
        n_devices = len(devices)
    else:
        devices = jax.devices()[:n_devices]
    overrides = dict(model_overrides or {})
    cfg = (llama.LlamaConfig.llama3_8b() if model_overrides is None
           else llama.LlamaConfig(**overrides))
    if cfg.n_kv_heads % n_devices:
        raise ValueError(f"kv heads {cfg.n_kv_heads} vs tensor={n_devices}")
    mesh = make_mesh(MeshConfig(tensor=n_devices), devices=devices)
    eng = _AbstractEngine(cfg, kv_quantize=kv_quantize)

    # -- weights: bf16 (cast) or weight-only int8, sharded by logical axes
    def build_params():
        p = llama.init(jax.random.key(0), cfg)
        p = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, p)
        if quantize == "int8":
            p = llama.quantize_params(p)
        return p

    p_sds = jax.eval_shape(build_params)
    p_sh = tree_logical_to_sharding(
        llama.logical_axes_for(p_sds, cfg), mesh)
    params = _abstract_tree(p_sds, p_sh)

    cache_sh = NamedSharding(mesh, P(None, None, None, "tensor"))
    repl = NamedSharding(mesh, P())
    # cache schema from the ONE source of truth (llama.init_cache) so the
    # proof can't drift from the layout the live engine allocates
    cache = {
        name: jax.ShapeDtypeStruct(sds.shape, sds.dtype, sharding=cache_sh)
        for name, sds in jax.eval_shape(
            lambda: llama.init_cache(cfg, n_slots, max_len,
                                     kv_quantize=kv_quantize)).items()}
    i32 = functools.partial(jax.ShapeDtypeStruct, dtype=jnp.int32,
                            sharding=repl)
    lengths, last = i32((n_slots,)), i32((n_slots,))
    temps = jax.ShapeDtypeStruct((n_slots,), jnp.float32, sharding=repl)
    key_sds = jax.eval_shape(lambda: jax.random.key(0))
    key = jax.ShapeDtypeStruct(key_sds.shape, key_sds.dtype, sharding=repl)
    wave = i32((width, bucket + 3))
    active = jax.ShapeDtypeStruct((n_slots,), jnp.bool_, sharding=repl)

    prefill_lowered = jax.jit(
        eng._prefill, donate_argnums=(1, 2, 3, 4, 5)).lower(
        params, cache, lengths, last, temps, key, wave)
    decode_lowered = jax.jit(
        functools.partial(eng._decode, steps=decode_steps),
        donate_argnums=(1, 2, 3, 4, 5)).lower(
        params, cache, lengths, last, temps, key, active)
    # chunked-prefill / prefix-cache continuation steps. Every chain
    # boundary compiles a DIFFERENT (p, t) program with a growing prefix
    # tensor, so the contract covers the FIRST boundary (p=bucket — the
    # prefix-cache hit shape) and the LARGEST possible boundary
    # (p = max_len - bucket — the worst-peak program of the longest
    # admissible prompt), plus the extract feeding it.
    cont_wave = i32((1, bucket + 3))

    def cont_lower(p):
        kv_prefix = jax.ShapeDtypeStruct(
            (cfg.n_layers, 1, p, cfg.n_kv_heads, cfg.head_dim),
            jnp.dtype(cfg.dtype), sharding=cache_sh)
        return jax.jit(
            eng._prefill_cont, donate_argnums=(1, 2, 3, 4, 5)).lower(
            params, cache, lengths, last, temps, key, cont_wave,
            kv_prefix, kv_prefix)

    p_max = max_len - bucket
    cont_lowered = cont_lower(bucket)
    cont_max_lowered = cont_lower(p_max)
    extract_lowered = jax.jit(
        functools.partial(eng._extract_prefix, p=p_max)).lower(
        cache, jax.ShapeDtypeStruct((), jnp.int32, sharding=repl))

    weight_bytes = sum(_leaf_device_bytes(l) for l in jax.tree.leaves(params))
    cache_bytes = sum(_leaf_device_bytes(l) for l in jax.tree.leaves(cache))
    report: dict[str, Any] = {
        "model": ("llama3-8b" if model_overrides is None
                  else f"llama-custom(d{cfg.d_model}xL{cfg.n_layers})"),
        "n_params": sum(
            math.prod(l.shape) for l in jax.tree.leaves(
                jax.eval_shape(lambda: llama.init(jax.random.key(0), cfg)))),
        "target": topology or str(devices[0].platform),
        "n_devices": n_devices,
        "tensor_parallel": n_devices,
        "weights": quantize or "bf16",
        "kv_cache": kv_quantize or str(jnp.dtype(cfg.dtype)),
        "n_slots": n_slots,
        "max_len": max_len,
        "prefill_bucket": bucket,
        "wave_width": width,
        "decode_steps": decode_steps,
        "weight_bytes_per_device": weight_bytes,
        "kv_cache_bytes_per_device": cache_bytes,
        "lowered": True,
    }
    if do_compile:
        peaks = {
            f"prefill_b{bucket}_w{width}": _peak(prefill_lowered.compile()),
            f"decode_x{decode_steps}": _peak(decode_lowered.compile()),
            f"cont_p{bucket}_t{bucket}": _peak(cont_lowered.compile()),
            f"cont_p{p_max}_t{bucket}": _peak(cont_max_lowered.compile()),
            f"extract_p{p_max}": _peak(extract_lowered.compile()),
        }
        report["compiled"] = True
        report["peak_bytes_per_device"] = peaks
        worst = max(peaks.values())
        report["worst_peak_bytes_per_device"] = worst
        report["v5e_hbm_bytes"] = V5E_HBM_BYTES
        report["fits_v5e_hbm"] = bool(worst <= V5E_HBM_BYTES)
    return report


if __name__ == "__main__":
    import json

    for q in (None, "int8"):
        print(json.dumps(aot_serving_report(quantize=q)))
