"""Serving contract proof: Llama-3-8B InferenceService on a v5e slice.

BASELINE config #5 is "InferenceService: Llama-3-8B"; no 8-chip slice exists
on a dev box, so — exactly like training/contract.py for config #3 — the
contract is proven against the REAL v5e compiler via PJRT topology AOT:

  - Build the engine's program menu (batched prefill wave + chained decode
    chunk — the same unbound methods LLMEngine compiles at runtime) at the
    true 8B dimensions, with params sharded by the model's logical axes and
    the KV cache sharded over kv-heads on a tensor=8 mesh.
  - AOT-compile each program for the v5e target and read XLA's buffer
    assignment: compile() itself enforces the HBM budget (RESOURCE_EXHAUSTED
    on an oversubscribed layout), and memory_analysis() reports the heap
    peak per device.
  - Account weights + KV cache residency analytically from the shardings.

Variants: weights as bf16 and weight-only int8 (ops/quant per-channel — the
production decode configuration).

Reference anchor (SURVEY.md §2.4 KServe + §2.6 Triton-class runtime row):
the reference serves 8B-class LLMs through kserve runtimes on GPU pools;
here the same contract is a mesh + logical-axis rules on the engine's
static program menu.
"""

from __future__ import annotations

import functools
import math
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from kubeflow_tpu.models import llama
from kubeflow_tpu.serving.llm import LLMEngine

V5E_HBM_BYTES = 16 * 1024**3


class _AbstractEngine:
    """Just enough instance surface to trace LLMEngine's program methods.
    The attributes reference the SAME unbound functions the live engine
    jits — the proof covers the production code path, not a re-derivation."""

    _prefill = LLMEngine._prefill
    _prefill_cont = LLMEngine._prefill_cont
    _unpack_wave = LLMEngine._unpack_wave
    _extract_prefix = LLMEngine._extract_prefix
    _decode = LLMEngine._decode
    _spec_decode = LLMEngine._spec_decode
    _cache_write = LLMEngine._cache_write
    _choose = LLMEngine._choose
    _pack_out = LLMEngine._pack_out
    _out_cols = LLMEngine._out_cols
    _constrain_cnt = LLMEngine._constrain_cnt

    def __init__(self, cfg: llama.LlamaConfig, kv_quantize: str | None = None,
                 *, n_slots: int = 0, max_len: int = 0,
                 speculative: int | None = None, adapters: bool = False):
        self.cfg = cfg
        self.mesh = None
        self.kv_quantize = kv_quantize
        # spec mode swaps the decode program for _spec_decode and adapters
        # add a rank-r gathered bypass to every matmul; both variants are
        # compiled by aot_serving_report when requested (r3 advisor: the
        # exclusion used to be asserted, not proven)
        self.spec = speculative
        self.spec_ngram = 3
        self.n_slots = n_slots
        self.max_len = max_len
        self.adapters = True if adapters else None
        self._row_extra = 9 if adapters else 8
        # production sampler defaults (serving/llm.py __init__)
        self.sample_k_max = 64
        self.logprobs_topk = 0


def _abstract_tree(tree, shardings):
    return jax.tree.map(
        lambda l, sh: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=sh),
        tree, shardings)


def _leaf_device_bytes(leaf) -> int:
    shard = leaf.sharding.shard_shape(leaf.shape)
    return math.prod(shard) * leaf.dtype.itemsize


def _peak(compiled) -> int:
    ma = compiled.memory_analysis()
    if ma is None:
        return 0
    peak = getattr(ma, "peak_memory_in_bytes", 0) or (
        ma.argument_size_in_bytes + ma.temp_size_in_bytes)
    return int(peak)


def aot_serving_report(
    topology: str | None = "v5e:2x4",
    *,
    quantize: str | None = None,
    kv_quantize: str | None = None,
    n_devices: int = 8,
    n_slots: int = 8,
    max_len: int = 8192,
    bucket: int = 2048,
    width: int = 4,
    decode_steps: int = 8,
    do_compile: bool = True,
    model_overrides: dict[str, Any] | None = None,
    speculative: int | None = None,
    n_adapters: int = 0,
    adapter_rank: int = 16,
) -> dict[str, Any]:
    """Compile the engine's 8B program menu for a v5e target; return the
    memory evidence. `topology=None` targets `n_devices` local devices
    instead (the CI virtual-CPU path)."""
    from kubeflow_tpu.parallel import MeshConfig
    from kubeflow_tpu.parallel.mesh import make_mesh
    from kubeflow_tpu.parallel.sharding import tree_logical_to_sharding

    if topology is not None:
        from jax.experimental import topologies

        devices = list(topologies.get_topology_desc(topology).devices)
        n_devices = len(devices)
    else:
        devices = jax.devices()[:n_devices]
    overrides = dict(model_overrides or {})
    cfg = (llama.LlamaConfig.llama3_8b() if model_overrides is None
           else llama.LlamaConfig(**overrides))
    if cfg.n_kv_heads % n_devices:
        raise ValueError(f"kv heads {cfg.n_kv_heads} vs tensor={n_devices}")
    mesh = make_mesh(MeshConfig(tensor=n_devices), devices=devices)
    eng = _AbstractEngine(cfg, kv_quantize=kv_quantize,
                          n_slots=n_slots, max_len=max_len)

    # one abstract trace of the full init, shared by the weight shardings,
    # the adapter target dims, and the n_params count
    init_sds = jax.eval_shape(lambda: llama.init(jax.random.key(0), cfg))

    # -- weights: bf16 (cast) or weight-only int8, sharded by logical axes
    def build_params(p):
        p = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, p)
        if quantize == "int8":
            p = llama.quantize_params(p)
        return p

    p_sds = jax.eval_shape(build_params, init_sds)
    p_sh = tree_logical_to_sharding(
        llama.logical_axes_for(p_sds, cfg), mesh)
    params = _abstract_tree(p_sds, p_sh)

    cache_sh = NamedSharding(mesh, P(None, None, None, "tensor"))
    repl = NamedSharding(mesh, P())
    # cache schema from the ONE source of truth (llama.init_cache) so the
    # proof can't drift from the layout the live engine allocates
    cache = {
        name: jax.ShapeDtypeStruct(sds.shape, sds.dtype, sharding=cache_sh)
        for name, sds in jax.eval_shape(
            lambda: llama.init_cache(cfg, n_slots, max_len,
                                     kv_quantize=kv_quantize)).items()}
    # per-slot penalty counts ride the cache vocab-sharded over `tensor`,
    # exactly the live engine's layout (_shard_over: _cnt_sh); the
    # abstract engines get the same mesh + constraint so the lowered
    # programs match production
    cnt_sh = NamedSharding(mesh, P(None, "tensor"))
    cache["cnt"] = jax.ShapeDtypeStruct((n_slots, cfg.vocab_size),
                                        jnp.int32, sharding=cnt_sh)
    eng.mesh, eng._cnt_sh = mesh, cnt_sh
    i32 = functools.partial(jax.ShapeDtypeStruct, dtype=jnp.int32,
                            sharding=repl)
    lengths, last = i32((n_slots,)), i32((n_slots,))
    # per-slot sampling state [temperature, top_k, top_p, presence,
    # frequency, seed]
    samp = jax.ShapeDtypeStruct((n_slots, 6), jnp.float32, sharding=repl)
    key_sds = jax.eval_shape(lambda: jax.random.key(0))
    key = jax.ShapeDtypeStruct(key_sds.shape, key_sds.dtype, sharding=repl)
    wave = i32((width, bucket + 8))
    active = jax.ShapeDtypeStruct((n_slots,), jnp.bool_, sharding=repl)

    prefill_lowered = jax.jit(
        eng._prefill, donate_argnums=(1, 2, 3, 4, 5)).lower(
        params, cache, lengths, last, samp, key, wave)
    decode_lowered = jax.jit(
        functools.partial(eng._decode, steps=decode_steps),
        donate_argnums=(1, 2, 3, 4, 5)).lower(
        params, cache, lengths, last, samp, key, active)
    # chunked-prefill / prefix-cache continuation steps. Every chain
    # boundary compiles a DIFFERENT (p, t) program with a growing prefix
    # tensor, so the contract covers the FIRST boundary (p=bucket — the
    # prefix-cache hit shape) and the LARGEST possible boundary
    # (p = max_len - bucket — the worst-peak program of the longest
    # admissible prompt), plus the extract feeding it.
    cont_wave = i32((1, bucket + 8))

    def cont_lower(p):
        kv_prefix = jax.ShapeDtypeStruct(
            (cfg.n_layers, 1, p, cfg.n_kv_heads, cfg.head_dim),
            jnp.dtype(cfg.dtype), sharding=cache_sh)
        return jax.jit(
            eng._prefill_cont, donate_argnums=(1, 2, 3, 4, 5)).lower(
            params, cache, lengths, last, samp, key, cont_wave,
            kv_prefix, kv_prefix)

    p_max = max_len - bucket
    cont_lowered = cont_lower(bucket)
    cont_max_lowered = cont_lower(p_max)
    extract_lowered = jax.jit(
        functools.partial(eng._extract_prefix, p=p_max)).lower(
        cache, jax.ShapeDtypeStruct((), jnp.int32, sharding=repl))

    extra_lowered: dict[str, Any] = {}
    if speculative:
        # the speculative verify program (scan of _spec_decode rounds) at
        # full span — the worst-HBM member of the spec menu: its verify
        # forward carries S_v = spec+1 query rows plus the history buffer
        spec_eng = _AbstractEngine(cfg, kv_quantize=kv_quantize,
                                   n_slots=n_slots, max_len=max_len,
                                   speculative=speculative)
        spec_eng.mesh, spec_eng._cnt_sh = mesh, cnt_sh
        spec_cache = dict(cache)
        spec_cache["hist"] = jax.ShapeDtypeStruct(
            (n_slots, max_len), jnp.int32, sharding=repl)
        extra_lowered[f"spec_k{speculative}_x{decode_steps}"] = jax.jit(
            functools.partial(spec_eng._spec_decode, steps=decode_steps,
                              span=max_len),
            donate_argnums=(1, 2, 3, 4, 5)).lower(
            params, spec_cache, lengths, last, samp, key, active)
    if n_adapters:
        # multi-adapter serving: the adapter stack rides as a trailing
        # program arg ([L, A+1, ...] per target, index 0 = zero adapter)
        # and the cache carries per-slot adapter ids. Target dims come from
        # the model's own (unquantized) layer leaves — one source of truth
        # for the layout, exactly like lora.init reads them.
        ad_eng = _AbstractEngine(cfg, kv_quantize=kv_quantize,
                                 n_slots=n_slots, max_len=max_len,
                                 adapters=True)
        ad_eng.mesh, ad_eng._cnt_sh = mesh, cnt_sh
        base_sds = init_sds
        lora = {}
        for t in ("wq", "wk", "wv", "wo"):
            _, di, do = base_sds["layers"][t].shape
            lora[t] = {"a": jax.ShapeDtypeStruct(
                           (cfg.n_layers, n_adapters + 1, di, adapter_rank),
                           jnp.float32, sharding=repl),
                       "b": jax.ShapeDtypeStruct(
                           (cfg.n_layers, n_adapters + 1, adapter_rank, do),
                           jnp.float32, sharding=repl)}
        ad_cache = dict(cache)
        ad_cache["aids"] = jax.ShapeDtypeStruct(
            (n_slots,), jnp.int32, sharding=repl)
        ad_wave = i32((width, bucket + 9))
        extra_lowered[f"adapter_prefill_a{n_adapters}_r{adapter_rank}"] = \
            jax.jit(ad_eng._prefill, donate_argnums=(1, 2, 3, 4, 5)).lower(
                params, ad_cache, lengths, last, samp, key, ad_wave, lora)
        extra_lowered[f"adapter_decode_a{n_adapters}_r{adapter_rank}"] = \
            jax.jit(functools.partial(ad_eng._decode, steps=decode_steps,
                                      span=max_len),
                    donate_argnums=(1, 2, 3, 4, 5)).lower(
                params, ad_cache, lengths, last, samp, key, active, lora)
        if speculative:
            # the live engine dispatches spec AND adapters in ONE program
            # (_do_decode's spec branch passes the adapter stack into
            # _spec_decode);
            # the combined member carries the spec+1 query rows, the hist
            # buffer, and the gathered rank-r bypass simultaneously — it,
            # not either variant alone, is the true worst of this menu
            both_eng = _AbstractEngine(cfg, kv_quantize=kv_quantize,
                                       n_slots=n_slots, max_len=max_len,
                                       speculative=speculative,
                                       adapters=True)
            both_eng.mesh, both_eng._cnt_sh = mesh, cnt_sh
            both_cache = dict(ad_cache)
            both_cache["hist"] = jax.ShapeDtypeStruct(
                (n_slots, max_len), jnp.int32, sharding=repl)
            extra_lowered[
                f"spec_k{speculative}_adapter_a{n_adapters}_x{decode_steps}"
            ] = jax.jit(
                functools.partial(both_eng._spec_decode, steps=decode_steps,
                                  span=max_len),
                donate_argnums=(1, 2, 3, 4, 5)).lower(
                params, both_cache, lengths, last, samp, key, active, lora)

    weight_bytes = sum(_leaf_device_bytes(l) for l in jax.tree.leaves(params))
    # KV bytes proper; the penalty-count buffer is auxiliary slot state,
    # itemized separately so the KV accounting stays exact
    cache_bytes = sum(_leaf_device_bytes(v) for n, v in cache.items()
                      if n != "cnt")
    if speculative or n_adapters:
        # the worst-peak member of the BASE menu is the largest-boundary
        # continuation (cont_p_max); its spec/adapter variant — extra
        # prefix-token wave columns + hist writes under spec, the gathered
        # rank-r bypass under adapters — is the true worst of the combined
        # menu, so it must be compiled too, not asserted to ride the margin
        worst_eng = _AbstractEngine(cfg, kv_quantize=kv_quantize,
                                    n_slots=n_slots, max_len=max_len,
                                    speculative=speculative,
                                    adapters=bool(n_adapters))
        worst_eng.mesh, worst_eng._cnt_sh = mesh, cnt_sh
        worst_cache = dict(cache)
        if speculative:
            worst_cache["hist"] = jax.ShapeDtypeStruct(
                (n_slots, max_len), jnp.int32, sharding=repl)
        if n_adapters:
            worst_cache["aids"] = jax.ShapeDtypeStruct(
                (n_slots,), jnp.int32, sharding=repl)
        ex = 9 if n_adapters else 8
        worst_wave = i32((1, bucket + (p_max if speculative else 0) + ex))
        worst_prefix = jax.ShapeDtypeStruct(
            (cfg.n_layers, 1, p_max, cfg.n_kv_heads, cfg.head_dim),
            jnp.dtype(cfg.dtype), sharding=cache_sh)
        worst_args = (params, worst_cache, lengths, last, samp, key,
                      worst_wave, worst_prefix, worst_prefix)
        if n_adapters:
            worst_args = worst_args + (lora,)
        worst_name = (f"cont_p{p_max}_t{bucket}"
                      + (f"_spec{speculative}" if speculative else "")
                      + (f"_a{n_adapters}" if n_adapters else ""))
        extra_lowered[worst_name] = jax.jit(
            worst_eng._prefill_cont,
            donate_argnums=(1, 2, 3, 4, 5)).lower(*worst_args)

    report: dict[str, Any] = {
        "model": ("llama3-8b" if model_overrides is None
                  else f"llama-custom(d{cfg.d_model}xL{cfg.n_layers})"),
        "n_params": sum(
            math.prod(l.shape) for l in jax.tree.leaves(init_sds)),
        "target": topology or str(devices[0].platform),
        "n_devices": n_devices,
        "tensor_parallel": n_devices,
        "weights": quantize or "bf16",
        "kv_cache": kv_quantize or str(jnp.dtype(cfg.dtype)),
        "n_slots": n_slots,
        "max_len": max_len,
        "prefill_bucket": bucket,
        "wave_width": width,
        "decode_steps": decode_steps,
        "speculative": speculative,
        "n_adapters": n_adapters,
        "weight_bytes_per_device": weight_bytes,
        "kv_cache_bytes_per_device": cache_bytes,
        "aux_state_bytes_per_device": _leaf_device_bytes(cache["cnt"]),
        "lowered": True,
    }
    if do_compile:
        peaks = {
            f"prefill_b{bucket}_w{width}": _peak(prefill_lowered.compile()),
            f"decode_x{decode_steps}": _peak(decode_lowered.compile()),
            f"cont_p{bucket}_t{bucket}": _peak(cont_lowered.compile()),
            f"cont_p{p_max}_t{bucket}": _peak(cont_max_lowered.compile()),
            f"extract_p{p_max}": _peak(extract_lowered.compile()),
        }
        peaks.update({name: _peak(low.compile())
                      for name, low in extra_lowered.items()})
        report["compiled"] = True
        report["peak_bytes_per_device"] = peaks
        worst = max(peaks.values())
        report["worst_peak_bytes_per_device"] = worst
        report["v5e_hbm_bytes"] = V5E_HBM_BYTES
        report["fits_v5e_hbm"] = bool(worst <= V5E_HBM_BYTES)
    return report


if __name__ == "__main__":
    import json

    for q in (None, "int8"):
        print(json.dumps(aot_serving_report(quantize=q)))
