"""LLM serving runtime — registers modelFormat "llama" so an
InferenceService predictor resolves to the continuous-batching engine
(SURVEY.md §2.4 runtime table: the huggingfaceserver/Triton-LLM slot).

    kind: InferenceService
    spec:
      predictor:
        model:
          modelFormat: llama
          config:
            model: {d_model: ..., n_layers: ...}   # LlamaConfig overrides
            n_slots: 4
            max_len: 512
            buckets: [64, 128, 256]
            checkpoint: /path/to/orbax/dir         # optional params source

V1/V2 payload: {"prompt_tokens": [...], "max_new_tokens": N} (or a list of
those). The engine thread runs continuous batching underneath, so
concurrent HTTP requests share decode steps; per-request TTFT lands in
Model.metrics() for the KServe-TTFT baseline metric (config #5).

Unified dataplane (ISSUE 12): by default the engine sits behind an
`EngineSupervisor` — every HTTP/SSE/gRPC/predict submission is
journaled, a mid-stream engine crash or stall triggers
journal→restart→idempotent replay while the SSE connection stays open
(keepalive comments during the restart window), and token emission
resumes from the journaled prefix with zero duplicate and zero lost
tokens. Greedy/seeded output through a crash is byte-identical to an
uncrashed run (the supervisor verifies the replayed prefix).
"""

from __future__ import annotations

import threading
import time
from typing import Any

from kubeflow_tpu.serving.model import Model, serving_runtime

# jax and the llama model module are imported inside load()/_load_params()
# so that registering this runtime (imported by kubeflow_tpu.serving for
# its side effect) keeps the serving package import jax-free.


class LLMModel(Model):
    def __init__(self, name: str, uri: str | None = None, *,
                 model: dict[str, Any] | None = None, n_slots: int = 4,
                 max_len: int = 512, buckets=(64, 128, 256),
                 eos_id: int | None = None, checkpoint: str | None = None,
                 seed: int = 0, timeout_s: float = 300.0,
                 mesh: dict[str, int] | None = None,
                 tokenizer: str | None = None,
                 prefix_cache: bool = False, max_prefixes: int = 4,
                 prefix_cache_blocks: int | None = None,
                 decode_chunk: int = 8,
                 quantize: str | None = None,
                 kv_quantize: str | None = None,
                 decode_attention_impl: str | None = None,
                 prefill_attention_impl: str | None = None,
                 speculative: int | None = None,
                 spec_ngram: int = 3,
                 spec_adaptive: bool = True,
                 lora: dict[str, Any] | None = None,
                 adapters: dict[str, Any] | None = None,
                 logprobs_topk: int = 0,
                 sample_k_max: int = 64,
                 pipeline_decode: bool = True,
                 compile_cache: str | None = None,
                 compile_cache_min_secs: float | None = None,
                 supervised: bool = True,
                 supervisor: dict[str, Any] | None = None,
                 sse_keepalive_s: float = 15.0,
                 disaggregated: bool = False,
                 disagg: dict[str, Any] | None = None,
                 usage_timing: bool = False,
                 kv_layout: str | None = None,
                 pool_blocks: int | None = None,
                 parallel: dict[str, Any] | None = None,
                 trace_sample_rate: float | None = None,
                 slo: dict[str, Any] | None = None,
                 **_ignored: Any):
        super().__init__(name)
        self._cfg_overrides = dict(model or {})
        self._mesh = dict(mesh) if mesh else None
        # text endpoints (/openai/v1/completions): byte-level fallback or
        # a local HF tokenizer dir (config.tokenizer)
        from kubeflow_tpu.serving.tokenizer import load_tokenizer

        self.tokenizer = load_tokenizer(tokenizer)
        self._n_slots = n_slots
        self._max_len = max_len
        self._buckets = tuple(buckets)
        self._eos_id = eos_id
        self._checkpoint = checkpoint or uri
        self._prefix_cache = prefix_cache
        self._max_prefixes = max_prefixes
        # config.prefix_cache_blocks: radix KV-reuse block-pool capacity
        # (None derives from max_prefixes — see LLMEngine)
        self._prefix_cache_blocks = prefix_cache_blocks
        self._decode_chunk = decode_chunk
        self._quantize = quantize
        self._kv_quantize = kv_quantize
        # config.decode_attention_impl (ISSUE 15): "xla" | "flash" |
        # "auto" — the serving decode/verify attention kernel selection.
        # It is a LlamaConfig field, so `model: {decode_attention_impl:
        # ...}` works too; this top-level key is the ergonomic spelling
        # (and wins over the model dict when both are given). "auto"
        # (the default) resolves flash on TPU / xla elsewhere, with the
        # KTPU_DECODE_ATTN env as the fleet kill-switch.
        if decode_attention_impl is not None:
            self._cfg_overrides["decode_attention_impl"] = \
                decode_attention_impl
        # config.prefill_attention_impl (ISSUE 20): the chunked-prefill
        # twin — same spelling rules and env kill-switch
        # (KTPU_PREFILL_ATTN) as decode_attention_impl.
        if prefill_attention_impl is not None:
            self._cfg_overrides["prefill_attention_impl"] = \
                prefill_attention_impl
        self._speculative = speculative
        self._spec_ngram = spec_ngram
        # config.spec_adaptive (default on): per-slot EMA acceptance
        # adapts the draft length k per verify round (serving/llm.py
        # AdaptiveDraftLen); off = static k, the pre-r6 behavior
        self._spec_adaptive = spec_adaptive
        # config.lora {rank, alpha, targets?}: the checkpoint is a
        # llama_lora fine-tune ({"base","lora"} tree); restore it and serve
        # the MERGED model — zero serving-path overhead, the engine never
        # knows LoRA existed
        self._lora = dict(lora) if lora else None
        # config.adapters {name: {checkpoint: <llama_lora ckpt dir>,
        # rank: r, alpha: a}}: multi-adapter serving — each request picks
        # an adapter ("adapter" in the payload), all share the base and
        # the continuous batch
        self._adapters_cfg = dict(adapters) if adapters else None
        self._logprobs_topk = logprobs_topk
        self._sample_k_max = sample_k_max
        self._pipeline_decode = pipeline_decode
        # config.compile_cache: persistent XLA compilation cache dir (the
        # Knative cold-start lever beyond in-process warmup): a restarted
        # predictor reloads its whole program menu from disk instead of
        # recompiling — at 8B dims that is ~37-90s of warmup down to
        # seconds on a warm cache
        self._compile_cache = compile_cache
        self._compile_cache_min_secs = compile_cache_min_secs
        # config.supervised (default ON — the unified-dataplane contract):
        # the engine sits behind serving/agent.EngineSupervisor, so every
        # HTTP/gRPC/predict submission is journaled and a mid-stream
        # engine crash replays instead of killing the client connection.
        # config.supervisor tunes it: {stall_timeout_s, stall_min_steps,
        # backoff_base_s, backoff_cap_s, max_restarts, stability_s,
        # rewarm}. rewarm (default True) re-runs the full warmup menu on
        # every restart — recovery is slower but no live request ever
        # waits on XLA; rewarm=False restarts cold and lets the replay
        # compile only the programs it touches (the fast-lane setting).
        self._supervised = supervised
        self._sup_cfg = dict(supervisor or {})
        # config.disaggregated (ISSUE 13): split serving into a
        # dedicated PREFILL worker (chunked prefill → radix KV blocks,
        # never a decode step) and a DECODE worker (admits via KV
        # handoff, never a full prefill in steady state), each behind
        # its own EngineSupervisor, coordinated by
        # serving/disagg.DisaggregatedEngine. config.disagg tunes it:
        # {handoff: zero_copy|serialized, prefill_slots: N,
        #  max_inflight_prefills: N}.
        self._disaggregated = bool(disaggregated)
        self._disagg_cfg = dict(disagg or {})
        if self._disaggregated and not supervised:
            raise ValueError(
                "disaggregated serving requires supervised: true (each "
                "role's crash story IS its supervisor)")
        # config.parallel {tensor: T, stage: P} (ISSUE 14): the tp×pp
        # engine layout. stage > 1 builds the stage-sharded engine
        # (serving/multichip.py) — per-stage params/KV slabs, microbatched
        # MPMD decode; stage == 1 with tensor > 1 is sugar for the
        # existing GSPMD tensor-parallel mesh path.
        self._parallel = dict(parallel or {})
        _pp_raw = self._parallel.get("stage")
        _tp_raw = self._parallel.get("tensor")
        pp = 1 if _pp_raw is None else int(_pp_raw)
        tp = 1 if _tp_raw is None else int(_tp_raw)
        if pp < 1 or tp < 1:
            raise ValueError("parallel.stage/parallel.tensor must be >= 1")
        if pp > 1 and self._disaggregated:
            raise ValueError(
                "parallel.stage > 1 does not compose with disaggregated "
                "serving yet (the stage pipeline IS the prefill/decode "
                "overlap mechanism)")
        if (pp > 1 or tp > 1) and self._mesh:
            # a silently-dropped tensor request would serve on an
            # unintended layout — reject every parallel+mesh combo
            raise ValueError("pass parallel OR mesh, not both")
        self._pp, self._tp = pp, tp
        if pp == 1 and tp > 1:
            self._mesh = {"tensor": tp}
        # config.kv_layout (ISSUE 19): "slab" (the preallocated
        # [n_slots, max_len] rows — the default) or "paged"
        # (block-granular pool + per-slot block tables with
        # oversubscribed admission, serving/paged.py). Explicit config
        # wins over the KTPU_KV_LAYOUT env (the fleet-wide rollout
        # lever); unset resolves slab. config.pool_blocks sizes the
        # paged pool (None = the slab's exact HBM footprint).
        import os

        resolved = kv_layout or os.environ.get("KTPU_KV_LAYOUT") or "slab"
        if resolved not in ("slab", "paged"):
            raise ValueError(
                f"kv_layout must be 'slab' or 'paged', got {resolved!r}")
        if resolved == "paged":
            if pp > 1:
                raise ValueError(
                    "kv_layout=paged does not compose with "
                    "parallel.stage > 1 yet: the stage-sharded engine "
                    "keeps per-stage KV slabs (serving/multichip.py)")
            if self._mesh:
                raise ValueError(
                    "kv_layout=paged does not compose with a mesh yet: "
                    "the block pool has no GSPMD layout")
            if self._disaggregated:
                raise ValueError(
                    "kv_layout=paged does not compose with disaggregated "
                    "serving yet: the prefill->decode handoff moves slab "
                    "rows (serving/disagg.py)")
        self._kv_layout = resolved
        self._pool_blocks = pool_blocks
        # config.usage_timing: surface the request_timing() phase split
        # (queue_wait_ms / prefill_ms / decode_ms) in the OpenAI usage
        # object; off (default) keeps the usage shape byte-unchanged
        self._usage_timing = bool(usage_timing)
        # config.sse_keepalive_s: max silence on a token stream before a
        # `: keepalive` SSE comment goes out — during a crash-restart
        # window the connection stays provably alive instead of tripping
        # client/proxy read timeouts
        self._sse_keepalive_s = float(sse_keepalive_s)
        # config.trace_sample_rate (ISSUE 17): fraction of trace ids the
        # process keeps spans for (deterministic per-id hash, so router/
        # supervisor/engine agree without coordination). None leaves the
        # process tracer's current rate alone — the tracer is
        # process-global, so only an explicit config value touches it.
        if trace_sample_rate is not None:
            from kubeflow_tpu.obs.trace import TRACER

            TRACER.set_sample_rate(float(trace_sample_rate))
        # config.slo {ttft_ms, tpot_ms, window_s, budget}: the online
        # burn tracker behind /healthz's "slo" section and the
        # slo_attainment / slo_burn_rate gauges
        from kubeflow_tpu.obs.metrics import add_scrape_hook
        from kubeflow_tpu.obs.slo import SloBurnTracker

        slo_cfg = dict(slo or {})
        self.slo_tracker = SloBurnTracker(
            ttft_slo_ms=float(slo_cfg.get("ttft_ms", 2000.0)),
            tpot_slo_ms=float(slo_cfg.get("tpot_ms", 200.0)),
            window_s=float(slo_cfg.get("window_s", 300.0)),
            budget=float(slo_cfg.get("budget", 0.01)))
        add_scrape_hook(self.slo_tracker, SloBurnTracker.publish)
        self._seed = seed
        self._timeout_s = timeout_s
        self._engine = None
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._loop_error: BaseException | None = None
        # rids whose waiter gave up (timeout/error) while still in flight;
        # the engine thread releases them once they finish — a waiter thread
        # must never release an unfinished request out from under the loop
        self._abandoned: set[int] = set()

    # -- lifecycle -----------------------------------------------------------

    def load(self) -> None:
        from kubeflow_tpu.models import llama
        from kubeflow_tpu.serving.llm import LLMEngine

        if self._compile_cache:
            import jax

            # keyed by HLO + compile flags, so correctness is unaffected;
            # process-global (jax has one cache), which is the right scope
            # for a predictor pod. reset_cache(): jax binds the cache
            # instance lazily to the dir at first use — a dir configured
            # after that would silently never be written
            jax.config.update("jax_compilation_cache_dir",
                              self._compile_cache)
            if self._compile_cache_min_secs is not None:
                # optional threshold override; left alone by default so an
                # operator's env/flag policy survives this predictor
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs",
                    self._compile_cache_min_secs)
            from jax.experimental.compilation_cache import (
                compilation_cache as _cc)

            _cc.reset_cache()
        mesh = None
        if self._mesh:
            # tensor-parallel predictor: config.mesh {tensor: N, ...}
            from kubeflow_tpu.parallel import MeshConfig
            from kubeflow_tpu.parallel.mesh import make_mesh

            mesh = make_mesh(MeshConfig(**self._mesh))
        if self._checkpoint and llama.is_hf_checkpoint(self._checkpoint):
            # HuggingFace-format dir (config.json + safetensors): weights,
            # architecture AND tokenizer come from one storageUri — the
            # huggingfaceserver slot (⊘ kserve python/huggingfaceserver).
            # The mesh goes INTO load_hf so an 8B checkpoint lands directly
            # sharded — materializing it whole first would OOM the chip the
            # sharding exists to relieve.
            cfg = llama.config_from_hf(self._checkpoint,
                                       **self._cfg_overrides)
            params, cfg = llama.load_hf(self._checkpoint, cfg, mesh=mesh)
            import os

            from kubeflow_tpu.serving.tokenizer import (ByteTokenizer,
                                                        load_tokenizer)

            if isinstance(self.tokenizer, ByteTokenizer) and os.path.exists(
                    os.path.join(self._checkpoint, "tokenizer.json")):
                self.tokenizer = load_tokenizer(self._checkpoint)
            if self._eos_id is None:
                self._eos_id = getattr(self.tokenizer, "eos_id", None)
        else:
            cfg = llama.LlamaConfig(**self._cfg_overrides)
            params = self._load_params(cfg)
        engine_kw = dict(n_slots=self._n_slots,
                         max_len=self._max_len,
                         buckets=self._buckets, eos_id=self._eos_id,
                         mesh=mesh,
                         decode_chunk=self._decode_chunk,
                         prefix_cache=self._prefix_cache,
                         max_prefixes=self._max_prefixes,
                         prefix_cache_blocks=self._prefix_cache_blocks,
                         quantize=self._quantize,
                         kv_quantize=self._kv_quantize,
                         speculative=self._speculative,
                         spec_ngram=self._spec_ngram,
                         spec_adaptive=self._spec_adaptive,
                         adapters=self._load_adapters(cfg),
                         logprobs_topk=self._logprobs_topk,
                         sample_k_max=self._sample_k_max,
                         pipeline_decode=self._pipeline_decode)
        # read, never pop: a second load() on this instance (unload →
        # reload is a legal Model lifecycle) must see the same config
        rewarm = bool(self._sup_cfg.get("rewarm", True))
        warmed: list[bool] = []

        def engine_factory():
            # the only sanctioned engine construction site on the
            # serving dataplane (scripts/check_dataplane.py enforces
            # this): engines are born inside a supervisor factory, so a
            # crash always has a recovery story. The first build always
            # warms (no live request waits on XLA at load); restarts
            # rewarm per config.supervisor.rewarm. config.parallel with
            # stage > 1 builds the tp×pp stage-sharded engine instead —
            # same supervision, journaling, and replay story.
            if self._pp > 1:
                from kubeflow_tpu.serving.multichip import \
                    StageShardedEngine

                # config.parallel.stage_schedule (ISSUE 20): "sync" |
                # "overlapped" wavefront dispatch; None defers to the
                # KTPU_STAGE_OVERLAP env, then the sync default
                eng = StageShardedEngine(
                    params, cfg, stage=self._pp, tensor=self._tp,
                    stage_schedule=self._parallel.get("stage_schedule"),
                    **engine_kw)
            elif self._kv_layout == "paged":
                from kubeflow_tpu.serving.paged import PagedLLMEngine

                eng = PagedLLMEngine(params, cfg,
                                     pool_blocks=self._pool_blocks,
                                     **engine_kw)
            else:
                eng = LLMEngine(params, cfg, **engine_kw)
            if rewarm or not warmed:
                eng.warmup()
                warmed.append(True)
            return eng

        if self._disaggregated:
            from kubeflow_tpu.serving.agent import EngineSupervisor
            from kubeflow_tpu.serving.disagg import DisaggregatedEngine
            from kubeflow_tpu.serving.llm import DecodeEngine, PrefillEngine

            dg = self._disagg_cfg
            pre_kw = dict(engine_kw, prefix_cache=True)
            if dg.get("prefill_slots"):
                pre_kw["n_slots"] = int(dg["prefill_slots"])
            dec_kw = dict(engine_kw, prefix_cache=True)
            warmed_roles: dict[str, bool] = {}

            def prefill_engine_factory():
                # role engines are born inside supervisor factories too
                # (scripts/check_dataplane.py lints all three names)
                eng = PrefillEngine(params, cfg, **pre_kw)
                if rewarm or not warmed_roles.get("prefill"):
                    eng.warmup()
                    warmed_roles["prefill"] = True
                return eng

            def decode_engine_factory():
                eng = DecodeEngine(params, cfg, **dec_kw)
                if rewarm or not warmed_roles.get("decode"):
                    eng.warmup()
                    warmed_roles["decode"] = True
                return eng

            sup_kw = {k: v for k, v in self._sup_cfg.items()
                      if k != "rewarm"}
            sup_kw.setdefault("stall_timeout_s", 10.0)
            self._engine = DisaggregatedEngine(
                EngineSupervisor(prefill_engine_factory, **sup_kw),
                EngineSupervisor(decode_engine_factory, **sup_kw),
                handoff=dg.get("handoff", "zero_copy"),
                max_inflight_prefills=dg.get("max_inflight_prefills"))
        elif self._supervised:
            from kubeflow_tpu.serving.agent import EngineSupervisor

            # a conservative default stall watchdog for the HTTP path:
            # the supervisor's own 2 s default is tuned for the bench's
            # warmed miniature engines, not arbitrary deployments
            sup_kw = {k: v for k, v in self._sup_cfg.items()
                      if k != "rewarm"}
            sup_kw.setdefault("stall_timeout_s", 10.0)
            self._engine = EngineSupervisor(engine_factory, **sup_kw)
        else:
            # escape hatch for benches/tests measuring the bare engine
            self._engine = engine_factory()
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"llm-engine-{self.name}")
        self._thread.start()
        self._mark_ready()

    def _load_adapters(self, cfg):
        """config.adapters -> engine adapter stacks: restore each named
        llama_lora checkpoint's ADAPTER subtree (the base stays the
        engine's own params — that is the whole point of multi-adapter
        serving)."""
        if not self._adapters_cfg:
            return None
        import jax

        from kubeflow_tpu.models import lora as lora_lib
        from kubeflow_tpu.serving.model import ModelError
        from kubeflow_tpu.training.checkpoint import restore_params

        out = {}
        for name, spec in self._adapters_cfg.items():
            lcfg = lora_lib.LoraLlamaConfig(
                rank=int(spec.get("rank", 8)),
                alpha=float(spec.get("alpha", 16.0)),
                targets=tuple(spec["targets"]) if "targets" in spec
                else lora_lib.LoraLlamaConfig.targets,
                llama=dict(self._cfg_overrides))
            abstract = jax.eval_shape(
                lambda lc=lcfg: lora_lib.init(jax.random.key(0), lc))
            try:
                restored = restore_params(spec["checkpoint"],
                                          {"lora": abstract["lora"]})
            except FileNotFoundError as e:
                raise ModelError(f"adapter {name!r}: {e}") from e
            out[name] = {"lora": restored["lora"], "alpha": lcfg.alpha}
        return out

    def _load_params(self, cfg):
        import jax

        from kubeflow_tpu.models import llama

        if self._lora is not None:
            # a llama_lora trainer checkpoint: restore {"base","lora"} and
            # merge the adapters into plain llama params
            from kubeflow_tpu.models import lora as lora_lib
            from kubeflow_tpu.serving.model import ModelError
            from kubeflow_tpu.training.checkpoint import restore_params

            if not self._checkpoint:
                raise ModelError("config.lora requires a checkpoint")
            lcfg_kw = dict(self._lora)
            # the trainer checkpoint already CONTAINS the base weights —
            # never re-read the original base here (eval_shape must stay IO
            # free)
            lcfg_kw.pop("base_checkpoint", None)
            if "targets" in lcfg_kw:
                lcfg_kw["targets"] = tuple(lcfg_kw["targets"])
            lcfg = lora_lib.LoraLlamaConfig(
                llama=dict(self._cfg_overrides), **lcfg_kw)
            abstract = jax.eval_shape(
                lambda: lora_lib.init(jax.random.key(0), lcfg))
            try:
                restored = restore_params(self._checkpoint, abstract)
            except FileNotFoundError as e:
                raise ModelError(str(e)) from e
            return lora_lib.merge(restored, lcfg,
                                  stop_base_gradient=False)
        if self._checkpoint:
            # orbax trainer checkpoint: restore the params subtree against
            # the model's abstract shapes (opt_state is not needed to
            # serve). A configured-but-empty checkpoint dir raises rather
            # than silently serving random weights.
            from kubeflow_tpu.serving.model import ModelError
            from kubeflow_tpu.training.checkpoint import restore_params

            abstract = jax.eval_shape(
                lambda: llama.init(jax.random.key(0), cfg))
            try:
                return restore_params(self._checkpoint, abstract)
            except FileNotFoundError as e:
                raise ModelError(str(e)) from e
        return llama.init(jax.random.key(self._seed), cfg)

    def _loop(self) -> None:
        try:
            while not self._stop.is_set():
                progressed = self._engine.step()
                self._sweep_abandoned()
                if not progressed:
                    # idle: sleep until a submit wakes us
                    self._wake.wait(timeout=0.02)
                    self._wake.clear()
        except BaseException as e:  # surface to waiting predict() calls
            self._loop_error = e
            raise

    def _sweep_abandoned(self) -> None:
        for rid in list(self._abandoned):
            if self._engine.is_done(rid):
                self._engine.release(rid)
                self._abandoned.discard(rid)

    def unload(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._engine is not None:
            try:
                self._engine.close()   # frees device buffers / journal
            except Exception:
                pass
        super().unload()

    @property
    def supervisor(self):
        """The EngineSupervisor under this model (None on the
        supervised=False escape hatch) — the chaos harness arms fault
        scripts here, and healthz reads its accounting. Under
        disaggregated serving this is the DECODE role's supervisor (the
        replica's identity); the prefill role rides
        `prefill_supervisor`."""
        from kubeflow_tpu.serving.agent import EngineSupervisor
        from kubeflow_tpu.serving.disagg import DisaggregatedEngine

        if isinstance(self._engine, DisaggregatedEngine):
            return self._engine.decode
        return (self._engine
                if isinstance(self._engine, EngineSupervisor) else None)

    @property
    def prefill_supervisor(self):
        """The prefill role's EngineSupervisor (disaggregated serving
        only; None otherwise) — the prefill-crash chaos drill arms fault
        scripts here."""
        from kubeflow_tpu.serving.disagg import DisaggregatedEngine

        return (self._engine.prefill
                if isinstance(self._engine, DisaggregatedEngine)
                else None)

    # -- inference -----------------------------------------------------------

    def predict(self, payload: Any) -> Any:
        if isinstance(payload, list):
            return [{"output_tokens": r["token_ids"]}
                    for r in self._submit_wait_all(payload)]
        return {"output_tokens": self._wait(self._submit(payload))}

    def _submit_wait_all(self, payloads: list) -> list[dict[str, Any]]:
        """Burst primitive shared by predict() and complete_many(): ALL
        requests submit before any wait, so they share prefill waves and
        decode steps instead of serializing. On any failure, everything
        not yet drained is cancelled (frees its decode slot at the next
        chunk boundary) and abandoned (the engine loop releases it)."""
        rids: list[int] = []
        out: list[dict[str, Any]] = []
        try:
            for p in payloads:
                rids.append(self._submit(p))
            for rid in rids:
                out.append(self._wait(rid, full=True))
        except BaseException:
            # a failed _wait abandons its own rid too; cancelling it again
            # is a no-op and re-adding to the set is harmless
            for rid in rids[len(out):]:
                self._engine.cancel(rid)
                self._abandoned.add(rid)
            raise
        return out

    def _encode_stops(self, stop: Any) -> list[list[int]]:
        """OpenAI `stop` (a string, a list of strings, or token-id lists)
        → engine stop sequences. Strings are tokenizer-encoded; for a
        byte/char tokenizer this is exact, for BPE a stop string spanning
        merge boundaries may not match token-aligned output (documented —
        the buffered path additionally truncates decoded TEXT)."""
        from kubeflow_tpu.serving.protocol import ProtocolError

        if stop is None:
            return []
        if isinstance(stop, str):
            stop = [stop]
        if not isinstance(stop, list):
            raise ProtocolError("stop must be a string or a list")
        out: list[list[int]] = []
        for s in stop:
            if isinstance(s, str):
                ids = self.tokenizer.encode(s)
                if ids:
                    out.append(list(ids))
            elif isinstance(s, list):
                out.append([int(t) for t in s])
            else:
                raise ProtocolError(
                    "stop entries must be strings or id lists")
        # client-controllable input: the engine's own bounds raise bare
        # ValueErrors that the HTTP layer deliberately maps to 500;
        # surface every violation as a 400 here instead
        if len(out) > 8:
            raise ProtocolError("at most 8 stop sequences per request")
        for seq in out:
            if not 1 <= len(seq) <= 64:
                raise ProtocolError(
                    "each stop sequence must encode to 1..64 tokens")
        return out

    def _submit(self, payload: Any) -> int:
        if not isinstance(payload, dict) or "prompt_tokens" not in payload:
            raise ValueError(
                "llama runtime expects {'prompt_tokens': [...], "
                "'max_new_tokens': N}")
        prompt = [int(t) for t in payload["prompt_tokens"]]
        max_new = int(payload.get("max_new_tokens", 32))
        temperature = float(payload.get("temperature", 0.0))
        adapter = payload.get("adapter")
        # engine-enforced deadline: even an abandoned/never-drained request
        # frees its decode slot once its wall budget passes. The implicit
        # backstop sits ABOVE timeout_s so the waiter's TimeoutError (the
        # client-visible contract) always fires first — a request must not
        # nondeterministically come back 200/"cancelled" instead
        deadline = float(payload.get("deadline_s")
                         or (self._timeout_s + 10.0))
        seed = payload.get("seed")
        # trace id: taken from the payload (the HTTP layer maps the
        # X-Trace-Id header here; the router minted it upstream) or
        # minted NOW — submit is the edge for direct predict()/gRPC
        # callers. Whether spans actually record is the sampler's call.
        from kubeflow_tpu.obs.trace import new_trace_id

        trace = str(payload.get("trace") or new_trace_id())
        rid = self._engine.submit(
            prompt, max_new, temperature, adapter=adapter,
            top_k=int(payload.get("top_k", 0)),
            top_p=float(payload.get("top_p", 1.0)),
            presence_penalty=float(payload.get("presence_penalty", 0.0)),
            frequency_penalty=float(payload.get("frequency_penalty", 0.0)),
            seed=None if seed is None else int(seed),
            stop=self._encode_stops(payload.get("stop")),
            deadline_s=deadline,
            tenant=payload.get("tenant"),
            trace=trace)
        self._wake.set()
        return rid

    def _check_alive(self, deadline: float) -> None:
        """One liveness/deadline gate for every waiter (buffered + stream)."""
        if (self._stop.is_set() or self._thread is None
                or not self._thread.is_alive()):
            raise RuntimeError(
                f"llm engine loop is not running ({self._loop_error!r})")
        if time.monotonic() >= deadline:
            raise TimeoutError(
                f"generation timed out after {self._timeout_s}s")

    def stream(self, payload: Any, on_finish=None, info: dict | None = None):
        """(token_id, logprob) stream for the SSE-completions backend.
        Submits EAGERLY (not a generator itself) so unservable requests —
        PromptTooLong, QueueFull — raise before the caller commits an
        HTTP status; returns the generator that drains the engine.
        `on_finish(reason)` fires before release with the OpenAI
        finish_reason ("stop" | "length" | "cancelled"). `info`, when
        given, is filled at finish time with per-request accounting the
        final SSE usage chunk carries (currently `cached_tokens` — KV
        tokens the prefix cache reused).

        With stop sequences, the last max(len(stop))-many tokens are held
        back until the request finishes: a stop match truncates the
        result, and held-back tokens are the only ones a match can
        remove — so the stream never emits text the buffered path would
        have trimmed."""
        stops = self._encode_stops(payload.get("stop"))
        if stops:   # encode ONCE; token-id lists pass through _submit's
            payload = dict(payload, stop=stops)   # _encode_stops unchanged
        rid = self._submit(payload)
        hold = max((len(s) for s in stops), default=0)
        return self._stream_from(rid, on_finish, hold, info)

    def _timing_fields(self, rid: int) -> dict[str, Any]:
        """The request's phase split for the usage object (read BEFORE
        release). Missing phases report as None — the engine fills them
        as the boundaries land."""
        try:
            tm = self._engine.request_timing(rid)
        except Exception:
            return {}
        return {k: tm.get(k) for k in
                ("queue_wait_ms", "prefill_ms", "handoff_ms",
                 "decode_ms")
                if k != "handoff_ms" or "handoff_ms" in tm}

    def _slo_record(self, rid: int, reason: str) -> None:
        """Feed one finished request into the burn tracker (read BEFORE
        release, like _timing_fields). Never raises — SLO accounting must
        not take down the serving path."""
        try:
            tm = self._engine.request_timing(rid)
        except Exception:
            return
        sub = tm.get("submit_s")
        first = tm.get("first_token_s")
        fin = tm.get("finish_s")
        n = tm.get("n_tokens") or 0
        ttft = ((first - sub) * 1e3
                if sub is not None and first is not None else None)
        tpot = ((fin - first) / (n - 1) * 1e3
                if first is not None and fin is not None and n >= 2
                else None)
        self.slo_tracker.record(tm.get("tenant"), ttft, tpot,
                                completed=reason in ("stop", "length"))

    def _cached_tokens(self, rid: int) -> int | None:
        """None when the engine runs no prefix cache (the usage object
        then omits cached_tokens entirely); 0 on a cache-on miss."""
        eng = self._engine
        if getattr(eng, "kvcache", None) is None \
                and not getattr(eng, "prefix_cache_enabled", False):
            return None
        fn = getattr(eng, "cached_tokens", None)
        return int(fn(rid)) if fn is not None else None

    def _stream_from(self, rid: int, on_finish=None, hold: int = 0,
                     info: dict | None = None):
        deadline = time.monotonic() + self._timeout_s
        sent = 0
        last_emit = time.monotonic()
        try:
            while True:
                done = self._engine.is_done(rid)   # BEFORE the drain: a
                # token landing between drain and check is caught next loop
                toks = self._engine.partial_result(rid)
                lps = self._engine.partial_logprobs(rid)
                limit = len(toks) if done else max(0, len(toks) - hold)
                if not done:
                    # the engine thread appends token-then-logprob; a
                    # snapshot between the two would otherwise emit a
                    # fabricated 0.0 — hold that token one poll instead
                    limit = min(limit, len(lps))
                while sent < limit:
                    yield toks[sent], (lps[sent] if sent < len(lps)
                                       else 0.0)
                    sent += 1
                    last_emit = time.monotonic()
                if done:
                    break
                if time.monotonic() - last_emit >= self._sse_keepalive_s:
                    # silence — typically a crash-restart window (backoff
                    # + rewarm) with the journal holding this stream: a
                    # (None, None) sentinel tells the HTTP layer to write
                    # an SSE keepalive comment so the client connection
                    # survives until token emission resumes, and gives it
                    # a beat to probe for client disconnect
                    yield None, None
                    last_emit = time.monotonic()
                self._check_alive(deadline)
                time.sleep(0.001)
        except BaseException:
            # a dropped SSE client (GeneratorExit via close()), a timeout,
            # or a dead loop: CANCEL so the decode slot frees at the next
            # chunk boundary instead of burning to max_new_tokens
            self._engine.cancel(rid)
            self._abandoned.add(rid)
            raise
        reason = self._engine.finish_reason(rid)
        if reason == "cancelled" and getattr(self._engine, "failed", False):
            # supervisor exhausted its restart budget mid-stream: the
            # client must see a TERMINAL error event, not a silent
            # "cancelled" that reads like its own disconnect (and never a
            # hang). The raise reaches _stream_completion's generic
            # error-chunk path; the abandoned sweep releases the rid.
            self._abandoned.add(rid)
            raise RuntimeError(
                "backend permanently failed (supervisor restart budget "
                "exhausted) after "
                f"{len(self._engine.partial_result(rid))} tokens")
        if info is not None:
            cached = self._cached_tokens(rid)
            if cached is not None:
                info["cached_tokens"] = cached
            if self._usage_timing:
                info["timing"] = self._timing_fields(rid)
        if on_finish is not None:
            on_finish(reason)
        self._slo_record(rid, reason)
        self._engine.release(rid)

    def complete(self, payload: Any) -> dict[str, Any]:
        """Buffered generation: {"token_ids", "finish_reason",
        "logprobs" (per-token raw-model logprobs) and, when the engine is
        built with logprobs_topk > 0, "top_logprobs"}."""
        rid = self._submit(payload)
        return self._wait(rid, full=True)

    def complete_many(self, payloads: list) -> list[dict[str, Any]]:
        """Buffered generation for a burst (the OpenAI n/best_of
        fan-out); see _submit_wait_all."""
        return self._submit_wait_all(payloads)

    def _wait(self, rid: int, full: bool = False):
        deadline = time.monotonic() + self._timeout_s
        try:
            while not self._engine.is_done(rid):
                self._check_alive(deadline)
                time.sleep(0.001)
        except BaseException:
            # free the slot promptly (deadline/error): see _stream_from
            self._engine.cancel(rid)
            self._abandoned.add(rid)  # engine thread releases it when done
            raise
        out = self._engine.result(rid)
        reason = self._engine.finish_reason(rid)
        result = {"token_ids": out, "finish_reason": reason,
                  "logprobs": self._engine.result_logprobs(rid)}
        cached = self._cached_tokens(rid)
        if cached is not None:
            # prompt tokens whose KV the prefix cache reused (0 on a
            # miss); absent entirely when the engine runs no cache, so
            # cache-off deployments keep their exact usage shape
            result["cached_tokens"] = cached
        if self._usage_timing:
            # the phase split rides the usage object only when the
            # operator turned it on (the r10 cached_tokens precedent:
            # the default usage shape stays byte-unchanged)
            result["timing"] = self._timing_fields(rid)
        if self._logprobs_topk:
            result["top_logprobs"] = self._engine.result_top_logprobs(rid)
        self._slo_record(rid, reason)
        self._engine.release(rid)  # long-lived server: drop request state
        return result if full else out

    def metrics(self) -> dict[str, Any]:
        return self._engine.metrics() if self._engine else {}


@serving_runtime("llama")
def _llama_runtime(name: str, uri: str | None = None,
                   **config: Any) -> Model:
    return LLMModel(name, uri, **config)
