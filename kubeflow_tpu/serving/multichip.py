"""Stage-sharded multichip serving (ISSUE 14, ROADMAP #2): tp×pp decode.

A 31B-class int8 llama geometry does not fit one chip, and a pure
tensor-parallel layout stops paying past the ICI-efficient group size —
the remaining single-replica scaling axis is PIPELINE stages. This
module promotes the GPipe stage split (parallel/pipeline.py) from a
training schedule to a first-class serving configuration:

  - `LLMEngine`'s compiled-program menu is re-pointed at PER-STAGE
    programs: stage s holds layers [lo_s, hi_s) as a params slab
    (tensor-sharded over its own sub-mesh when `tensor` > 1 — the
    `("stage", "tensor")` mesh spec) plus that slab's KV cache
    [L_s, slots, max_len, kv, hd] — the cache is threaded per-stage,
    never materialized whole;
  - decode runs MPMD-style: the active wave splits into pp microbatches
    of slots and flows through the stages on the GPipe wavefront
    (parallel/pipeline.wavefront), so stage k decodes microbatch i while
    stage k-1 decodes microbatch i+1 — per-stage programs dispatch async
    onto disjoint device groups, which is what overlaps them on real
    hardware. Prefill waves pipeline through the same stages (each
    wave's stage-0 program dispatches before earlier waves fetch), so
    chunked prefill chains fill decode's bubbles instead of stalling
    behind a monolithic program;
  - sampling/penalties/stop/cancel/radix logic is NOT duplicated: the
    drivers reuse every host-side engine mechanism and the models/llama
    `*_inner` bodies, so greedy/seeded output is byte-exact against the
    single-program engine (the bench.py serving_multichip floor);
  - prefix-KV reuse stays correct under pp: blocks bank per-stage with
    the stage id IN the radix block key (kvcache.StagePartitionedKVCache
    — namespace (ns, stage)), so a cached chain always materializes the
    right slab slices and uneven eviction truncates to the common
    prefix.

Like every engine, `StageShardedEngine` may only be constructed inside
a supervisor factory (scripts/check_dataplane.py lints the name);
`llm_runtime` builds it from `config.parallel: {tensor: T, stage: P}`.

Not supported (loudly): speculative decoding and multi-adapter LoRA —
both thread extra per-step device state (history buffer, adapter
stacks) through the single program; their stage-sharded forms are
follow-on work, and the single-program engine keeps serving them.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Any

import jax
import jax.numpy as jnp

from kubeflow_tpu.kvcache import RadixKVCache, StagePartitionedKVCache
from kubeflow_tpu.models import llama
from kubeflow_tpu.obs.trace import TRACER
from kubeflow_tpu.parallel.pipeline import (InferenceStagePlan, StageClock,
                                            resolve_schedule,
                                            split_stage_params, wavefront)
from kubeflow_tpu.serving.llm import LLMEngine


class StageShardedEngine(LLMEngine):
    """Continuous-batching engine whose model forward is decomposed into
    `stage` per-stage compiled programs, each optionally tensor-sharded
    over its own sub-mesh. Drop-in for LLMEngine everywhere the
    dataplane cares (submit/step/cancel/metrics/request_timing), with
    byte-exact greedy/seeded output."""

    role = "stage_sharded"

    def __init__(self, params, cfg: llama.LlamaConfig, *, stage: int = 2,
                 tensor: int = 1, devices=None, stage_timing: bool = False,
                 stage_schedule: str | None = None, **kw):
        if kw.get("speculative"):
            raise ValueError(
                "speculative decoding is not supported with stage "
                "parallelism (the history buffer threads the single "
                "program); serve spec traffic on the single-program "
                "engine")
        if kw.get("adapters"):
            raise ValueError(
                "multi-adapter serving is not supported with stage "
                "parallelism yet")
        if kw.get("mesh") is not None:
            raise ValueError(
                "StageShardedEngine owns its mesh: pass stage=/tensor=, "
                "not mesh=")
        kw.pop("mesh", None)
        if kw.pop("kv_layout", "slab") != "slab":
            # ISSUE 19 boundary: the paged block pool is single-device
            # (one pool, one table, one donation chain); per-stage
            # pools are a follow-up. Stage KV stays slab rows.
            raise ValueError(
                "StageShardedEngine keeps per-stage KV SLABS: "
                "kv_layout=paged is not supported with stage "
                "parallelism (serving/paged.py is single-program)")
        if tensor > 1 and cfg.n_kv_heads % tensor:
            raise ValueError(
                f"n_kv_heads={cfg.n_kv_heads} must divide by the tensor "
                f"axis ({tensor}) to shard the per-stage KV slabs")
        n_slots = int(kw.get("n_slots", 4))
        if tensor > 1 and cfg.decode_attention_impl == "auto":
            # per-stage programs with tensor > 1 are GSPMD-sharded over
            # the stage sub-mesh — same reason the base engine's mesh
            # path pins "auto" to the einsum: a pallas custom call has
            # no SPMD partitioning rule yet (ROADMAP #5's remaining
            # half). tensor == 1 stages run whole on one device and
            # take the kernel like the single-program engine.
            import dataclasses

            cfg = dataclasses.replace(cfg, decode_attention_impl="xla")
        if tensor > 1 and cfg.prefill_attention_impl == "auto":
            # same boundary for the prefill kernel (ISSUE 20): "auto"
            # pins to the mha einsum under tensor sharding; an explicit
            # "flash" is honored — the operator owns the layout claim
            import dataclasses

            cfg = dataclasses.replace(cfg, prefill_attention_impl="xla")
        # -- stage schedule (ISSUE 20): "sync" walks the wavefront with
        # per-program blocking when timing is armed (the r13 shape);
        # "overlapped" keeps every dispatch async — stage s's program
        # for microbatch m+1 enters the queue while m's outputs are
        # still in flight — and times per-stage dispatch→drain windows
        # instead. Resolution: explicit ctor arg > KTPU_STAGE_OVERLAP
        # env > sync (default off — the KTPU_DECODE_ATTN seam pattern).
        self.stage_schedule = resolve_schedule(stage_schedule)
        # geometry + placement first: _alloc_cache/_put run inside the
        # base __init__ and need the plan
        self._plan = InferenceStagePlan(cfg.n_layers, stage, n_slots,
                                        tensor=tensor, devices=devices)
        self._plan.perf.schedule = self.stage_schedule
        self.n_stages = self._plan.n_stages
        self.tensor = self._plan.tensor
        self.stage_timing = bool(stage_timing)
        self._home_sharding = self._plan.replicated(self.n_stages - 1)
        self._cnt_sh_stage = None
        last_sm = self._plan.submeshes[-1]
        if last_sm is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            # penalty counts shard over vocab on the LAST stage's
            # sub-mesh, like the lm_head logits they edit (the base
            # engine's _cnt_sh, scoped to the tail programs' mesh)
            self._cnt_sh_stage = NamedSharding(last_sm, P(None, "tensor"))
        self._stage_progs: dict[tuple, Any] = {}
        self._tail_progs: dict[tuple, Any] = {}
        self._slabs: list[dict] | None = None
        super().__init__(params, cfg, **kw)
        # split the (possibly int8-quantized) stack into per-stage slabs
        # placed on their sub-meshes; the full tree is dropped — drivers
        # only ever read self._slabs (self.params aliases it so close()
        # and the profiler's weight-read probe see the real residency)
        log_full = llama.logical_axes_for(self.params, cfg)
        raw = split_stage_params(self.params, self._plan.bounds)
        slabs = []
        for s, slab in enumerate(raw):
            logical = {"layers": log_full["layers"]}
            if s == 0:
                logical["embed"] = log_full["embed"]
            if s == self.n_stages - 1:
                logical["final_norm"] = log_full["final_norm"]
                logical["lm_head"] = log_full["lm_head"]
            slabs.append(self._plan.shard_slab(slab, s, logical))
        self._slabs = slabs
        self.params = slabs
        if self._home_sharding is not None:
            self.rng_key = jax.device_put(self.rng_key,
                                          self._home_sharding)
        if self.prefix_cache_enabled and self.kvcache is not None:
            # stage-id enters the radix block key: one shared pool, each
            # logical block stored once per stage slab. Capacity scales
            # by pp so the LOGICAL capacity the operator configured is
            # preserved (a logical block costs pp physical blocks).
            self.kvcache = StagePartitionedKVCache(
                RadixKVCache(self.prefix_block_tokens,
                             self.kvcache.capacity_blocks * self.n_stages),
                self.n_stages)

    # -- placement ------------------------------------------------------------

    def _put(self, x):
        """Host array → the engine's HOME devices (the last stage's
        sub-mesh, where the sampler tail runs); plain asarray under
        virtual staging."""
        if self._home_sharding is None:
            return jnp.asarray(x)
        return jax.device_put(jnp.asarray(x), self._home_sharding)

    def _constrain_cnt(self, cnt):
        if self._cnt_sh_stage is None:
            return cnt
        return jax.lax.with_sharding_constraint(cnt, self._cnt_sh_stage)

    def _alloc_cache(self):
        """Per-stage KV slabs [L_s, slots, max_len, kv, hd] (+ int8
        scale planes), each allocated on ITS stage's sub-mesh — a cache
        that only fits stage-sharded never exists whole. The sampler
        state (penalty counts) lives with the tail programs on the last
        stage."""
        stages = []
        for s, (lo, hi) in enumerate(self._plan.bounds):
            scfg = dataclasses.replace(self.cfg, n_layers=hi - lo)
            slab = llama.init_cache(scfg, self.n_slots, self.max_len,
                                    kv_quantize=self.kv_quantize)
            sh = self._plan.cache_sharding(s)
            if sh is not None:
                slab = {k: jax.device_put(v, sh) for k, v in slab.items()}
            stages.append(slab)
        cnt = jnp.zeros((self.n_slots, self.cfg.vocab_size), jnp.int32)
        if self._cnt_sh_stage is not None:
            cnt = jax.device_put(cnt, self._cnt_sh_stage)
        return {"stages": stages, "cnt": cnt}

    # -- per-stage compiled programs ------------------------------------------

    def _stage_prefill_prog(self, s: int, bucket: int, width: int):
        key = ("prefill", s, bucket, width)
        if key not in self._stage_progs:
            first = s == 0
            last = s == self.n_stages - 1

            def run(slab, cache_slab, wave, x_in):
                tokens, slots, prompt_lens, _row_samp, _aids = \
                    self._unpack_wave(wave)
                positions = jnp.arange(bucket)
                x = (slab["embed"].astype(self.cfg.dtype)[tokens]
                     if first else x_in)
                x, (ks, vs) = llama.prefill_inner(slab["layers"], x,
                                                  positions, self.cfg)
                cache_slab = dict(cache_slab)
                for i in range(width):   # W is static: unrolled updates
                    cache_slab = self._cache_write(
                        cache_slab, slots[i], 0, bucket, ks[:, i], vs[:, i])
                if last:
                    logits = llama.lm_head(slab, x, self.cfg)
                    lasts = [jax.lax.dynamic_index_in_dim(
                        logits[i], prompt_lens[i] - 1, keepdims=False)
                        for i in range(width)]
                    return cache_slab, jnp.stack(lasts)
                return cache_slab, x

            if first:
                fn = jax.jit(lambda slab, c, wave: run(slab, c, wave, None),
                             donate_argnums=(1,))
            else:
                fn = jax.jit(run, donate_argnums=(1,))
            self._stage_progs[key] = fn
        return self._stage_progs[key]

    def _stage_cont_prog(self, s: int, p: int, t: int, width: int):
        key = ("cont", s, p, t, width)
        if key not in self._stage_progs:
            first = s == 0
            last = s == self.n_stages - 1

            def run(slab, cache_slab, wave, k_prefix, v_prefix, x_in):
                tokens, slots, prompt_lens, _row_samp, _aids = \
                    self._unpack_wave(wave)
                positions = p + jnp.arange(t)
                x = (slab["embed"].astype(self.cfg.dtype)[tokens]
                     if first else x_in)
                x, (ks, vs) = llama.prefill_continue_inner(
                    slab["layers"], x, k_prefix, v_prefix, positions,
                    self.cfg)
                cache_slab = dict(cache_slab)
                for i in range(width):
                    cache_slab = self._cache_write(
                        cache_slab, slots[i], 0, p,
                        k_prefix[:, i], v_prefix[:, i])
                    cache_slab = self._cache_write(
                        cache_slab, slots[i], p, t, ks[:, i], vs[:, i])
                if last:
                    logits = llama.lm_head(slab, x, self.cfg)
                    lasts = [jax.lax.dynamic_index_in_dim(
                        logits[i], prompt_lens[i] - p - 1, keepdims=False)
                        for i in range(width)]
                    return cache_slab, jnp.stack(lasts)
                return cache_slab, x

            if first:
                fn = jax.jit(lambda slab, c, wave, kp, vp:
                             run(slab, c, wave, kp, vp, None),
                             donate_argnums=(1,))
            else:
                fn = jax.jit(run, donate_argnums=(1,))
            self._stage_progs[key] = fn
        return self._stage_progs[key]

    def _stage_dec_prog(self, s: int, m: int, span: int):
        """Stage s's decode program for microbatch m: embed (first) /
        activations in, slab-attention against the stage's KV slab rows
        [mb_start, mb_start+mb_size), logits out (last). The slab is the
        FULL-slot cache; verify_inner's slot_start windows it."""
        mb_start, mb_size = self._plan.mb_ranges[m]
        key = ("dec", s, mb_start, mb_size, span)
        if key not in self._stage_progs:
            first = s == 0
            last = s == self.n_stages - 1

            def run(slab, cache_slab, x_in, lengths):
                lengths_mb = jax.lax.slice_in_dim(
                    lengths, mb_start, mb_start + mb_size, axis=0)
                if first:
                    toks = jax.lax.slice_in_dim(
                        x_in, mb_start, mb_start + mb_size, axis=0)
                    x = slab["embed"].astype(self.cfg.dtype)[toks[:, None]]
                else:
                    x = x_in
                x, new_cache = llama.verify_inner(
                    slab["layers"], x, cache_slab, lengths_mb, self.cfg,
                    span=span, slot_start=mb_start)
                if last:
                    return new_cache, llama.lm_head(slab, x,
                                                    self.cfg)[:, 0]
                return new_cache, x

            self._stage_progs[key] = jax.jit(run, donate_argnums=(1,))
        return self._stage_progs[key]

    def _tail_prefill_prog(self, cols: int, width: int):
        """The shared sampler tail after a (continuation) prefill wave's
        last stage: exactly the single program's post-forward sequence —
        lengths/samp updates, _choose over the gathered last-row logits,
        penalty-count reset, packed output rows."""
        key = ("tail_prefill", cols, width)
        if key not in self._tail_progs:
            def run(stacked, wave, lengths, last_tokens, samp, key_, cnt):
                _toks, slots, prompt_lens, row_samp, _aids = \
                    self._unpack_wave(wave)
                for i in range(width):
                    lengths = lengths.at[slots[i]].set(prompt_lens[i])
                    samp = samp.at[slots[i]].set(row_samp[i])
                zero_cnt = jnp.zeros((width, cnt.shape[1]), cnt.dtype)
                key_, toks = self._choose(stacked, row_samp, key_, slots,
                                          zero_cnt, prompt_lens)
                for i in range(width):
                    last_tokens = last_tokens.at[slots[i]].set(toks[i])
                    cnt = cnt.at[slots[i]].set(jax.nn.one_hot(
                        toks[i], cnt.shape[1], dtype=cnt.dtype))
                return (lengths, last_tokens, samp, key_,
                        self._constrain_cnt(cnt),
                        self._pack_out(toks, stacked))

            self._tail_progs[key] = jax.jit(
                run, donate_argnums=(2, 3, 4, 5, 6))
        return self._tail_progs[key]

    def _tail_dec_prog(self, sample: bool = True):
        key = ("tail_dec", sample)
        if key not in self._tail_progs:
            def run(logits, lengths, last_tokens, samp, key_, cnt, active):
                slots = jnp.arange(self.n_slots)
                if sample:
                    key_, toks = self._choose(logits, samp, key_, slots,
                                              cnt, lengths + 1)
                    cnt = self._constrain_cnt(jax.lax.cond(
                        jnp.any((samp[:, 3] != 0) | (samp[:, 4] != 0)),
                        lambda c: c.at[slots, toks].add(
                            active.astype(c.dtype)),
                        lambda c: c, cnt))
                else:
                    toks = jnp.argmax(logits, -1).astype(jnp.int32)
                lengths = lengths + active.astype(jnp.int32)
                last_tokens = jnp.where(active, toks, last_tokens)
                return (lengths, last_tokens, key_, cnt,
                        self._pack_out(toks, logits))

            self._tail_progs[key] = jax.jit(
                run, donate_argnums=(1, 2, 4, 5))
        return self._tail_progs[key]

    # -- drivers (the engine menu's stage-sharded twins) ----------------------
    # Same call signatures as the single jitted programs, so step()/
    # warmup()/_do_decode()/profiling drive them unchanged. Dispatches
    # are async (the host never fetches inside a driver), so stage
    # programs of successive waves/microbatches overlap on disjoint
    # device groups; StageClock only blocks when stage_timing is armed.

    def _prefill_fn(self, bucket: int, width: int):
        if (bucket, width) not in self._prefill_fns:
            def driver(_params, cache, lengths, last_tokens, samp, key_,
                       wave):
                # no StageClock here: the bubble accounting is DECODE-
                # scoped (prefill waves pipeline through the same
                # stages, but their busy wall must not inflate the
                # decode pipeline's busy/idle split)
                clk = StageClock(self._plan.perf, False)
                stages = cache["stages"]
                x = None
                for s in range(self.n_stages):
                    prog = self._stage_prefill_prog(s, bucket, width)
                    wave_s = self._plan.to_stage(wave, s)
                    if s == 0:
                        res = clk.run(s, lambda p=prog, w=wave_s, s=s:
                                      p(self._slabs[s], stages[s], w))
                    else:
                        x_s = self._plan.to_stage(x, s)
                        res = clk.run(s, lambda p=prog, w=wave_s, x=x_s,
                                      s=s:
                                      p(self._slabs[s], stages[s], w, x))
                    stages[s], x = res
                (lengths, last_tokens, samp, key_, cache["cnt"], out) = \
                    self._tail_prefill_prog(wave.shape[1], width)(
                        x, wave, lengths, last_tokens, samp, key_,
                        cache["cnt"])
                return cache, lengths, last_tokens, samp, key_, out

            self._prefill_fns[bucket, width] = driver
        return self._prefill_fns[bucket, width]

    def _cont_fn(self, p: int, t: int, width: int):
        if (p, t, width) not in self._cont_fns:
            def driver(_params, cache, lengths, last_tokens, samp, key_,
                       wave, k_prefix, v_prefix):
                clk = StageClock(self._plan.perf, False)  # decode-scoped
                # timing, same as the prefill driver
                stages = cache["stages"]
                x = None
                for s in range(self.n_stages):
                    prog = self._stage_cont_prog(s, p, t, width)
                    wave_s = self._plan.to_stage(wave, s)
                    if s == 0:
                        res = clk.run(
                            s, lambda pr=prog, w=wave_s, s=s:
                            pr(self._slabs[s], stages[s], w,
                               k_prefix[s], v_prefix[s]))
                    else:
                        x_s = self._plan.to_stage(x, s)
                        res = clk.run(
                            s, lambda pr=prog, w=wave_s, x=x_s, s=s:
                            pr(self._slabs[s], stages[s], w,
                               k_prefix[s], v_prefix[s], x))
                    stages[s], x = res
                (lengths, last_tokens, samp, key_, cache["cnt"], out) = \
                    self._tail_prefill_prog(wave.shape[1], width)(
                        x, wave, lengths, last_tokens, samp, key_,
                        cache["cnt"])
                return cache, lengths, last_tokens, samp, key_, out

            self._cont_fns[p, t, width] = driver
        return self._cont_fns[p, t, width]

    def _decode_driver(self, steps: int, span: int, sample: bool):
        S, M = self.n_stages, self._plan.n_microbatches
        overlapped = self.stage_schedule == "overlapped"

        def driver(_params, cache, lengths, last_tokens, samp, key_,
                   active):
            clk = StageClock(self._plan.perf,
                             self.stage_timing and not overlapped)
            stages = cache["stages"]
            outs = []
            for _step in range(steps):
                t_step = time.perf_counter()
                # pre-step slot state, staged onto each sub-mesh; the
                # tail advances it once per step (one _choose per step =
                # the single program's key stream, so seeded sampling
                # parity survives microbatching)
                lengths_s = [self._plan.to_stage(lengths, s)
                             for s in range(S)]
                lt0 = self._plan.to_stage(last_tokens, 0)
                acts: list = [None] * M
                # overlapped timing: per-stage dispatch→drain windows
                # (first dispatch timestamp, last output blocked AFTER
                # the whole wavefront is in flight) instead of sync
                # mode's serializing per-program brackets — the windows
                # overlap, which is exactly what the bubble re-measure
                # is after (ISSUE 20)
                t_first: list = [None] * S
                last_out: list = [None] * S
                for _tick, s, m in wavefront(M, S):
                    prog = self._stage_dec_prog(s, m, span)
                    x_in = (lt0 if s == 0
                            else self._plan.to_stage(acts[m], s))
                    if overlapped:
                        # async dispatch, never block mid-wavefront:
                        # stage s's program for microbatch m+1 enters
                        # the stream while m's outputs are in flight
                        if t_first[s] is None:
                            t_first[s] = time.perf_counter()
                        res = prog(self._slabs[s], stages[s], x_in,
                                   lengths_s[s])
                    else:
                        res = clk.run(s, lambda p=prog, x=x_in, s=s:
                                      p(self._slabs[s], stages[s], x,
                                        lengths_s[s]))
                    stages[s], acts[m] = res
                    last_out[s] = acts[m]
                if overlapped and self.stage_timing:
                    for s in range(S):
                        jax.block_until_ready(last_out[s])
                        self._plan.perf.record_stage(
                            s, time.perf_counter() - t_first[s])
                logits = (acts[0] if M == 1
                          else jnp.concatenate(acts, axis=0))
                (lengths, last_tokens, key_, cache["cnt"], out) = \
                    self._tail_dec_prog(sample)(
                        logits, lengths, last_tokens, samp, key_,
                        cache["cnt"], active)
                outs.append(out)
                self._plan.perf.record_step(
                    M, time.perf_counter() - t_step)
            return cache, lengths, last_tokens, samp, key_, outs

        return driver

    def _decode_fn(self, steps: int, span: int | None = None):
        span = self.max_len if span is None else span
        if (steps, span) not in self._decode_fns:
            self._decode_fns[steps, span] = self._decode_driver(
                steps, span, sample=True)
        return self._decode_fns[steps, span]

    def _decode_nosample_fn(self, steps: int, span: int | None = None):
        span = self.max_len if span is None else span
        return self._decode_driver(steps, span, sample=False)

    # -- prefix-KV plumbing (per-stage payloads) ------------------------------

    def _extract_fn(self, p: int):
        if p not in self._extract_fns:
            prog = jax.jit(functools.partial(self._extract_prefix, p=p))

            def driver(cache, slot):
                ks, vs = [], []
                for s in range(self.n_stages):
                    k, v = prog(cache["stages"][s], slot)
                    ks.append(k)
                    vs.append(v)
                return ks, vs

            self._extract_fns[p] = driver
        return self._extract_fns[p]

    def _extract_raw_fn(self, p: int):
        if p not in self._extract_raw_fns:
            prog = jax.jit(functools.partial(self._extract_prefix_raw,
                                             p=p))

            def driver(cache, slot):
                return [prog(cache["stages"][s], slot)
                        for s in range(self.n_stages)]

            self._extract_raw_fns[p] = driver
        return self._extract_raw_fns[p]

    def _materialize_prefix(self, payloads: list):
        """payloads: list over blocks of per-stage payload tuples (the
        stage-keyed store's currency) → per-stage prefix arrays
        ([k_s, ...], [v_s, ...]) for the stage continuation programs."""
        ks, vs = [], []
        for blocks in zip(*payloads):   # [stage] -> that stage's chain
            k, v = self._materialize_payloads(
                list(blocks), self.kv_quantize, self.cfg.dtype)
            ks.append(k)
            vs.append(v)
        return ks, vs

    def _stack_prefix(self, entries: list):
        ks = [jnp.concatenate([e[0][s] for e in entries], axis=1)
              for s in range(self.n_stages)]
        vs = [jnp.concatenate([e[1][s] for e in entries], axis=1)
              for s in range(self.n_stages)]
        return ks, vs

    @staticmethod
    def _payload_slice(parts, s: int, e: int):
        """parts: per-stage raw-extract tuples; the block payload is the
        per-stage tuple of token-axis slices."""
        return tuple(tuple(a[:, :, s:e] for a in sp) for sp in parts)

    # -- observability --------------------------------------------------------

    def mesh_info(self) -> dict[str, Any]:
        d = self._plan.describe()
        slab_bytes = ([int(sum(l.nbytes for l in jax.tree.leaves(s)))
                       for s in self._slabs]
                      if self._slabs is not None else [])
        return {
            "layout": f"tp{self.tensor}xpp{self.n_stages}",
            "axes": {"stage": self.n_stages, "tensor": self.tensor},
            "device_count": d["device_count"],
            "virtual_stages": d["virtual"],
            "stage_layers": d["stage_layers"],
            "microbatches": d["microbatches"],
            "params_bytes": int(sum(slab_bytes)),
            "per_stage_params_bytes": slab_bytes,
        }

    def warmup(self) -> None:
        """Base warmup through the stage drivers, then a perf reset:
        warmup's junk decode chunks (and their XLA compiles, when
        stage_timing is armed) must not pollute the committed bubble
        accounting."""
        super().warmup()
        self._plan.perf.reset()

    def pipeline_perf(self, reset: bool = False) -> dict[str, Any]:
        """Per-stage busy/idle accounting (the pipeline_bubble_frac
        surface — measured when `stage_timing` is on, schedule-derived
        always)."""
        snap = self._plan.perf.snapshot()
        snap["microbatches"] = self._plan.n_microbatches
        snap["stage_timing"] = self.stage_timing
        if reset:
            self._plan.perf.reset()
        return snap

    def metrics(self) -> dict[str, Any]:
        out = super().metrics()
        out["pipeline"] = self.pipeline_perf()
        return out

    def _obs_finish(self, req_id: int) -> None:
        """Base per-request spans plus one retrospective ``stage`` span
        per pipeline stage over the request's decode window — emitted at
        finish from the plan geometry, NEVER from inside the wavefront
        loop (per-microbatch spans at decode rate are exactly what the
        sampling design forbids)."""
        trace = self._req_trace.get(req_id)
        first = self._first_token_t.get(req_id)
        fin = self._finish_t.get(req_id)
        super()._obs_finish(req_id)
        if trace is None or first is None or fin is None \
                or not TRACER.sampled(trace):
            return
        perf = self._plan.perf
        for s, (lo, hi) in enumerate(self._plan.bounds):
            TRACER.record_span(
                f"{self.role}.stage{s}", "stage", trace, first, fin,
                stage=s, layers=[lo, hi],
                microbatches=self._plan.n_microbatches,
                tensor=self.tensor,
                schedule_bubble_frac=perf.schedule_bubble_frac())

    def close(self) -> None:
        self._stage_progs.clear()
        self._tail_progs.clear()
        self._slabs = None
        super().close()
