"""Open Inference Protocol gRPC dataplane (SURVEY.md §2.4/§2.6: the
reference serves V2 over both REST and gRPC — kserve `kserve/protocol/grpc`,
Triton's GRPCInferenceService; SURVEY §2.2 keeps gRPC as the native control-
plane transport since grpcio's C++ core is in the image).

No grpcio-tools in the image, so service wiring is hand-registered with
`grpc.method_handlers_generic_handler` over protoc-generated message
classes (kubeflow_tpu/serving/protos/inference_pb2.py — regenerate with
scripts/gen_protos.sh).

The server shares ModelRepository/DynamicBatcher semantics with the HTTP
ModelServer: same models, same predict path, two dataplanes — exactly the
kserve layout.
"""

from __future__ import annotations

import threading
from concurrent import futures
from typing import Any

import numpy as np

from kubeflow_tpu.serving.model import Model, ModelError, ModelRepository
from kubeflow_tpu.serving.protocol import (InferRequest, InferResponse,
                                           InferTensor, ProtocolError,
                                           _DTYPES)
from kubeflow_tpu.serving.protos import inference_pb2 as pb

SERVICE = "inference.GRPCInferenceService"

# OIP datatype -> InferTensorContents field
_CONTENTS_FIELD = {
    "BOOL": "bool_contents",
    "INT8": "int_contents", "INT16": "int_contents", "INT32": "int_contents",
    "INT64": "int64_contents",
    "UINT8": "uint_contents", "UINT16": "uint_contents",
    "UINT32": "uint_contents", "UINT64": "uint64_contents",
    "FP16": "fp32_contents",  # FP16 rides the fp32 field, per the OIP spec
    "FP32": "fp32_contents", "FP64": "fp64_contents",
    "BYTES": "bytes_contents",
}


def _tensor_from_pb(t: "pb.ModelInferRequest.InferInputTensor") -> InferTensor:
    dt = t.datatype
    if dt not in _CONTENTS_FIELD:
        raise ProtocolError(f"unknown datatype {dt!r}")
    values = list(getattr(t.contents, _CONTENTS_FIELD[dt]))
    shape = tuple(t.shape)
    try:
        if dt == "BYTES":
            arr = np.array(values, dtype=object).reshape(shape)
        else:
            arr = np.array(values, dtype=_DTYPES[dt]).reshape(shape)
    except ValueError as e:
        raise ProtocolError(
            f"tensor {t.name!r}: {len(values)} values do not fit shape "
            f"{list(shape)} ({e})") from e
    return InferTensor(name=t.name, data=arr, datatype=dt)


def _tensor_to_pb(out: "pb.ModelInferResponse.InferOutputTensor",
                  t: InferTensor) -> None:
    out.name = t.name
    out.datatype = t.datatype
    out.shape.extend(int(d) for d in np.asarray(t.data).shape)
    field = _CONTENTS_FIELD.get(t.datatype)
    if field is None:
        raise ProtocolError(f"unknown datatype {t.datatype!r}")
    flat = np.asarray(t.data).reshape(-1)
    if t.datatype == "BYTES":
        getattr(out.contents, field).extend(
            v if isinstance(v, bytes) else str(v).encode() for v in flat)
    elif t.datatype == "BOOL":
        getattr(out.contents, field).extend(bool(v) for v in flat)
    elif t.datatype in ("FP16", "FP32", "FP64"):
        getattr(out.contents, field).extend(float(v) for v in flat)
    else:
        getattr(out.contents, field).extend(int(v) for v in flat)


class GrpcInferenceServer:
    """OIP gRPC server over a ModelRepository."""

    def __init__(self, repository: ModelRepository | None = None,
                 port: int = 0, host: str = "127.0.0.1",
                 max_workers: int = 8,
                 batching: dict[str, Any] | None = None):
        import grpc

        self.repository = repository or ModelRepository()
        # same per-model DynamicBatcher config shape as the HTTP ModelServer,
        # so both dataplanes share batching semantics
        self._batch_cfg = batching or {}
        self._batchers: dict[str, Any] = {}
        self._batch_lock = threading.Lock()
        self._grpc = grpc
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers))
        handlers = {
            "ServerLive": self._unary(self._server_live,
                                      pb.ServerLiveRequest,
                                      pb.ServerLiveResponse),
            "ServerReady": self._unary(self._server_ready,
                                       pb.ServerReadyRequest,
                                       pb.ServerReadyResponse),
            "ModelReady": self._unary(self._model_ready,
                                      pb.ModelReadyRequest,
                                      pb.ModelReadyResponse),
            "ModelMetadata": self._unary(self._model_metadata,
                                         pb.ModelMetadataRequest,
                                         pb.ModelMetadataResponse),
            "ModelInfer": self._unary(self._model_infer,
                                      pb.ModelInferRequest,
                                      pb.ModelInferResponse),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, handlers),))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        self.host = host
        self._started = False

    def _unary(self, fn, req_cls, resp_cls):
        return self._grpc.unary_unary_rpc_method_handler(
            fn, request_deserializer=req_cls.FromString,
            response_serializer=resp_cls.SerializeToString)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "GrpcInferenceServer":
        self._server.start()
        self._started = True
        return self

    def stop(self, grace: float = 0.5) -> None:
        if self._started:
            self._server.stop(grace).wait()
            self._started = False

    # -- rpc impls -----------------------------------------------------------

    def _server_live(self, request, context):
        return pb.ServerLiveResponse(live=True)

    def _server_ready(self, request, context):
        # the HTTP /v2/health/ready contract, same gate: LLM models
        # serve through their EngineSupervisor (the gRPC dataplane
        # shares the Model with HTTP, so every ModelInfer already
        # submits through it), and a supervisor whose restart budget is
        # exhausted makes this replica permanently not-ready —
        # ModelRepository.permanently_failed is the ONE definition both
        # frontends consult
        ready = all(self.repository.ready(n)
                    for n in self.repository.names()) \
            and not self.repository.permanently_failed()
        return pb.ServerReadyResponse(ready=ready)

    def _model_ready(self, request, context):
        return pb.ModelReadyResponse(
            ready=self.repository.ready(request.name))

    def _model_metadata(self, request, context):
        try:
            model = self.repository.get(request.name)
        except ModelError as e:
            context.abort(self._grpc.StatusCode.NOT_FOUND, str(e))
        resp = pb.ModelMetadataResponse(name=model.name,
                                        platform="kubeflow-tpu")
        for spec, field in ((model.input_spec(), resp.inputs),
                            (model.output_spec(), resp.outputs)):
            for s in spec:
                tm = field.add()
                tm.name = s.get("name", "")
                tm.datatype = s.get("datatype", "")
                tm.shape.extend(int(d) for d in s.get("shape", []))
        return resp

    def _predictor(self, model: Model):
        cfg = self._batch_cfg.get(model.name)
        if not cfg:
            return model.predict
        from kubeflow_tpu.serving.batching import DynamicBatcher

        with self._batch_lock:
            if model.name not in self._batchers:
                self._batchers[model.name] = DynamicBatcher(
                    model.predict,
                    max_batch_size=int(cfg.get("maxBatchSize", 16)),
                    max_latency_ms=float(cfg.get("maxLatencyMs", 5.0)))
            return self._batchers[model.name]

    def _model_infer(self, request, context):
        try:
            if request.raw_input_contents:
                context.abort(
                    self._grpc.StatusCode.INVALID_ARGUMENT,
                    "raw_input_contents not supported; send typed "
                    "InferTensorContents")
            model = self.repository.get(request.model_name)
            if not model.ready:
                context.abort(self._grpc.StatusCode.UNAVAILABLE,
                              f"model {request.model_name!r} not ready")
            req = InferRequest(
                model_name=request.model_name,
                inputs=[_tensor_from_pb(t) for t in request.inputs],
                id=request.id)
            payload = model.preprocess(req.as_dict())
            result = model.postprocess(self._predictor(model)(payload))
            resp_obj = InferResponse.from_result(request.model_name, result,
                                                 id=request.id)
            resp = pb.ModelInferResponse(model_name=resp_obj.model_name,
                                         id=resp_obj.id)
            for t in resp_obj.outputs:
                _tensor_to_pb(resp.outputs.add(), t)
            return resp
        except ModelError as e:
            context.abort(self._grpc.StatusCode.NOT_FOUND, str(e))
        except ProtocolError as e:
            context.abort(self._grpc.StatusCode.INVALID_ARGUMENT, str(e))


class GrpcInferenceClient:
    """Minimal OIP gRPC client (the kserve InferenceGRPCClient analog)."""

    def __init__(self, address: str, timeout: float = 30.0):
        import grpc

        self._channel = grpc.insecure_channel(address)
        self.timeout = timeout

        def m(name, req_cls, resp_cls):
            return self._channel.unary_unary(
                f"/{SERVICE}/{name}",
                request_serializer=req_cls.SerializeToString,
                response_deserializer=resp_cls.FromString)

        self._live = m("ServerLive", pb.ServerLiveRequest,
                       pb.ServerLiveResponse)
        self._ready = m("ServerReady", pb.ServerReadyRequest,
                        pb.ServerReadyResponse)
        self._model_ready = m("ModelReady", pb.ModelReadyRequest,
                              pb.ModelReadyResponse)
        self._metadata = m("ModelMetadata", pb.ModelMetadataRequest,
                           pb.ModelMetadataResponse)
        self._infer = m("ModelInfer", pb.ModelInferRequest,
                        pb.ModelInferResponse)

    def server_live(self) -> bool:
        return self._live(pb.ServerLiveRequest(), timeout=self.timeout).live

    def model_ready(self, name: str) -> bool:
        return self._model_ready(pb.ModelReadyRequest(name=name),
                                 timeout=self.timeout).ready

    def model_metadata(self, name: str):
        return self._metadata(pb.ModelMetadataRequest(name=name),
                              timeout=self.timeout)

    def infer(self, model_name: str,
              inputs: dict[str, np.ndarray] | list[InferTensor],
              id: str = "") -> dict[str, np.ndarray]:
        if isinstance(inputs, dict):
            inputs = [InferTensor(name=k, data=np.asarray(v))
                      for k, v in inputs.items()]
        req = pb.ModelInferRequest(model_name=model_name, id=id)
        for t in inputs:
            _tensor_to_pb(req.inputs.add(), t)
        resp = self._infer(req, timeout=self.timeout)
        return {t.name: _tensor_from_pb(t).data for t in resp.outputs}

    def close(self) -> None:
        self._channel.close()
