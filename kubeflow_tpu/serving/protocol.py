"""Inference protocols — KServe V1 and V2/Open Inference Protocol codecs
(SURVEY.md §2.4, ⊘ kserve `python/kserve/kserve/protocol/{rest,grpc}` and
the Open Inference Protocol spec KServe/Triton share).

V1 (legacy kserve):   POST /v1/models/<m>:predict   {"instances": [...]}
                      → {"predictions": [...]}
V2 (open inference):  POST /v2/models/<m>/infer
                      {"inputs": [{"name","shape","datatype","data"}, ...]}
                      → {"model_name", "outputs": [...]}

Tensors are numpy-backed. The same codec feeds REST (json) and the native
gRPC front-end, mirroring how kserve shares its dataplane between
transports.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import numpy as np

_DTYPES = {
    "BOOL": np.bool_, "UINT8": np.uint8, "UINT16": np.uint16,
    "UINT32": np.uint32, "UINT64": np.uint64, "INT8": np.int8,
    "INT16": np.int16, "INT32": np.int32, "INT64": np.int64,
    "FP16": np.float16, "FP32": np.float32, "FP64": np.float64,
    "BYTES": object,
}
_NP_TO_DTYPE = {np.dtype(v).name: k for k, v in _DTYPES.items()
                if v is not object}
_NP_TO_DTYPE["bool"] = "BOOL"


class ProtocolError(ValueError):
    pass


def dtype_of(arr: np.ndarray) -> str:
    if arr.dtype == object or arr.dtype.kind in ("U", "S"):
        return "BYTES"
    name = arr.dtype.name
    if name not in _NP_TO_DTYPE:
        raise ProtocolError(f"unsupported numpy dtype {name}")
    return _NP_TO_DTYPE[name]


@dataclass
class InferTensor:
    name: str
    data: np.ndarray
    datatype: str = ""
    parameters: dict[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        if not self.datatype:
            self.datatype = dtype_of(self.data)

    @classmethod
    def from_json(cls, obj: dict[str, Any]) -> "InferTensor":
        for key in ("name", "shape", "datatype", "data"):
            if key not in obj:
                raise ProtocolError(f"tensor missing {key!r}")
        dt = obj["datatype"]
        if dt not in _DTYPES:
            raise ProtocolError(f"unknown datatype {dt!r}")
        np_dt = _DTYPES[dt]
        arr = np.asarray(obj["data"],
                         dtype=np_dt if np_dt is not object else None)
        try:
            arr = arr.reshape(obj["shape"])
        except ValueError as e:
            raise ProtocolError(f"tensor {obj['name']}: {e}")
        return cls(name=obj["name"], data=arr, datatype=dt,
                   parameters=obj.get("parameters", {}))

    def to_json(self) -> dict[str, Any]:
        return {"name": self.name, "shape": list(self.data.shape),
                "datatype": self.datatype,
                "data": self.data.ravel().tolist(),
                **({"parameters": self.parameters} if self.parameters else {})}


@dataclass
class InferRequest:
    model_name: str
    inputs: list[InferTensor]
    id: str = ""
    parameters: dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_json(cls, model_name: str, obj: dict[str, Any]) -> "InferRequest":
        if "inputs" not in obj or not isinstance(obj["inputs"], list):
            raise ProtocolError("request missing inputs list")
        return cls(model_name=model_name,
                   inputs=[InferTensor.from_json(t) for t in obj["inputs"]],
                   id=obj.get("id", ""),
                   parameters=obj.get("parameters", {}))

    def to_json(self) -> dict[str, Any]:
        return {"id": self.id, "inputs": [t.to_json() for t in self.inputs],
                **({"parameters": self.parameters} if self.parameters else {})}

    def as_dict(self) -> dict[str, np.ndarray]:
        return {t.name: t.data for t in self.inputs}


@dataclass
class InferResponse:
    model_name: str
    outputs: list[InferTensor]
    id: str = ""
    parameters: dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_result(cls, model_name: str, result: Any,
                    id: str = "") -> "InferResponse":
        """Adapt predict() return values: tensor dict, single array, or a
        ready-made InferResponse."""
        if isinstance(result, InferResponse):
            return result
        if isinstance(result, dict):
            outs = [InferTensor(name=k, data=np.asarray(v))
                    for k, v in result.items()]
        else:
            outs = [InferTensor(name="output0", data=np.asarray(result))]
        return cls(model_name=model_name, outputs=outs, id=id)

    def to_json(self) -> dict[str, Any]:
        return {"model_name": self.model_name, "id": self.id,
                "outputs": [t.to_json() for t in self.outputs],
                **({"parameters": self.parameters} if self.parameters else {})}


# -- V1 (instances/predictions) ----------------------------------------------

def v1_decode(obj: dict[str, Any]) -> Any:
    if "instances" not in obj:
        raise ProtocolError('V1 request must carry "instances"')
    return obj["instances"]


def v1_encode(result: Any) -> dict[str, Any]:
    if isinstance(result, np.ndarray):
        result = result.tolist()
    elif isinstance(result, dict):
        result = {k: (v.tolist() if isinstance(v, np.ndarray) else v)
                  for k, v in result.items()}
    return {"predictions": result}
