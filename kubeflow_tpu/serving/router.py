"""Traffic router — the Istio ingress + Knative activator analog (SURVEY.md
§3.5: "Istio ingress ⇉ Knative activator/queue-proxy (concurrency,
scale-from-zero)").

One Router per InferenceService: an HTTP reverse proxy that
  - splits traffic between the default and canary backends by percentage
    (deterministic modular schedule, so a 20% canary gets exactly every
    5th request — testable, no RNG flakes);
  - on scale-to-zero services, calls the activator hook to spin the backend
    up on first request and records last-request time for idle scale-down;
  - health-gates every backend behind a per-port circuit breaker
    (closed → open → half-open, the chaos tentpole): transport-level
    failures trip the circuit, an open circuit takes no traffic for an
    escalating hold-off, and one half-open probe decides whether it
    closes again. When EVERY circuit in the eligible pool is open the
    router answers 503 with a Retry-After header pointing at the soonest
    half-open instant — back-pressure with a schedule, not a dropped
    connection;
  - pins sessions to replicas by RENDEZVOUS HASHING (the kvcache
    tentpole's placement half): a request carrying a stable session key
    (`X-Session-Key` header, else the JSON body's `session`, else the
    OpenAI `user` field) ranks the scheduled pool by
    hash(session_key, port) and takes the highest-ranked ADMITTING
    backend — so repeat traffic from one session/tenant lands where its
    prefix KV already lives and the radix cache actually hits. The
    affinity is stateless: when the affine replica's circuit opens, the
    next-ranked healthy replica takes over (no 503 while capacity
    remains), and the moment the circuit closes again the original
    ranking — and the pin — restores itself. Keyless requests keep the
    round-robin spread;
  - relays SSE completion streams PROGRESSIVELY (the unified-dataplane
    tentpole: the streaming path crosses the router too) with
    stream-aware failover — a backend failure before the first token
    reached the client retries the same request on the next candidate
    (affinity order preserved), a failure after first token emits a
    typed `mid_stream_failure` event carrying `tokens_delivered` so the
    client can resume, then [DONE];
  - groups backends into ZONES (`set_zones`) so a scripted
    `zone_outage` fault window makes a whole zone unreachable at once —
    the fleet-chaos drill: many circuits open simultaneously, traffic
    fails over to the surviving zone, and recovery is the breakers'
    ordinary half-open cycle.
"""

from __future__ import annotations

import hashlib
import http.client
import json
import math
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable

from kubeflow_tpu.obs import metrics as obs_metrics
from kubeflow_tpu.obs.build import build_stamp
from kubeflow_tpu.obs.metrics import render_metrics
from kubeflow_tpu.obs.trace import TRACE_HEADER, TRACER, new_trace_id

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


def _rendezvous_rank(pool: list[int], session_key: str) -> list[int]:
    """Highest-random-weight ordering of `pool` for one session key:
    every router ranks identically (blake2b is stable across processes
    and platforms), each key gets an independent pseudo-random
    permutation (load spreads across sessions), and removing a backend
    only moves the sessions that were pinned to it — the minimal-
    disruption property consistent placement exists for."""
    def weight(port: int) -> int:
        h = hashlib.blake2b(f"{session_key}|{port}".encode(),
                            digest_size=8)
        return int.from_bytes(h.digest(), "big")

    return sorted(pool, key=weight, reverse=True)


class _Circuit:
    """Per-backend breaker state. Not self-locking — the Router's lock
    covers every transition (state changes are tiny; the proxied request
    itself runs outside the lock)."""

    def __init__(self, failure_threshold: int, open_s: float,
                 open_cap_s: float, backend: str = ""):
        self.failure_threshold = failure_threshold
        self.base_open_s = open_s
        self.open_cap_s = open_cap_s
        self.backend = backend       # metric label (the port, stringly)
        self.state = CLOSED
        self.failures = 0            # consecutive transport failures
        self.opened_count = 0        # times this circuit tripped (metric)
        self.open_until = 0.0
        self.open_s = open_s
        self.probing = False         # a half-open probe is in flight
        obs_metrics.CIRCUIT_STATE.set(
            obs_metrics.CIRCUIT_STATE_CODES[CLOSED], backend=backend)

    def _transition(self, new: str) -> None:
        if new == self.state:
            return
        self.state = new
        obs_metrics.CIRCUIT_STATE.set(
            obs_metrics.CIRCUIT_STATE_CODES[new], backend=self.backend)
        obs_metrics.CIRCUIT_TRANSITIONS.inc(backend=self.backend, to=new)

    def admits(self, now: float) -> bool:
        """May a request be sent to this backend right now?"""
        if self.state == CLOSED:
            return True
        if self.state == OPEN and now >= self.open_until:
            # hold-off over: become half-open, admit ONE probe
            self._transition(HALF_OPEN)
            self.probing = False
        if self.state == HALF_OPEN and not self.probing:
            return True
        return False

    def on_attempt(self, now: float) -> None:
        if self.state == HALF_OPEN:
            self.probing = True

    def on_success(self) -> None:
        self._transition(CLOSED)
        self.failures = 0
        self.probing = False
        self.open_s = self.base_open_s   # recovery resets the escalation

    def on_failure(self, now: float) -> None:
        self.failures += 1
        self.probing = False
        if self.state == HALF_OPEN:
            # failed probe: reopen with doubled hold-off (capped)
            self.open_s = min(self.open_cap_s, self.open_s * 2.0)
            self._trip(now)
        elif self.failures >= self.failure_threshold:
            self._trip(now)

    def _trip(self, now: float) -> None:
        if self.state != OPEN:
            self.opened_count += 1
        self._transition(OPEN)
        self.open_until = now + self.open_s

    def retry_in(self, now: float) -> float:
        return max(0.0, self.open_until - now)


class Router:
    def __init__(self, name: str, port: int = 0,
                 activator: Callable[[], int | None] | None = None,
                 activation_timeout: float = 30.0,
                 failure_threshold: int = 3,
                 circuit_open_s: float = 0.5,
                 circuit_open_cap_s: float = 30.0):
        self.name = name
        self.activator = activator
        self.activation_timeout = activation_timeout
        self.failure_threshold = failure_threshold
        self.circuit_open_s = circuit_open_s
        self.circuit_open_cap_s = circuit_open_cap_s
        self._lock = threading.Lock()
        self._default_ports: list[int] = []
        self._canary_ports: list[int] = []
        self._canary_percent = 0
        self._count = 0
        # separate round-robin cursors per pool: a shared cursor plus a
        # deterministic canary schedule can phase-lock and starve a replica
        self._rr_default = 0
        self._rr_canary = 0
        self._circuits: dict[int, _Circuit] = {}
        self.canary_count = 0
        self.total_count = 0
        self.breaker_rejected = 0     # 503s served with every circuit open
        # session-affinity accounting: keyed requests that landed on
        # their rendezvous-first replica vs ones that failed over to a
        # lower-ranked healthy replica (circuit open / partition)
        self.affinity_hits = 0
        self.affinity_failovers = 0
        self.last_request_time: float = 0.0
        # optional chaos injector: an active "partition" event makes the
        # target backend unreachable from THIS router (the fault is in the
        # network path, so it must be injected here, not in the backend);
        # an active "zone_outage" event does the same for every backend
        # in the targeted zone at once (fleet chaos: many circuits open
        # simultaneously)
        self.fault_injector = None
        self._zone_of: dict[int, str] = {}
        # stream relay accounting (the stream-aware failover surface)
        self.stream_failovers = 0      # retried before first token
        self.stream_midfailures = 0    # typed error event after first token
        # concurrency tracking for the autoscaler (Knative queue-proxy
        # reports concurrency; here the router IS the queue-proxy)
        self.inflight = 0
        self.peak_inflight = 0
        self._start_mono = time.monotonic()
        # pull-model gauge refresh at scrape time (weakref-held: a
        # stopped router drops out of the hook list by itself)
        obs_metrics.add_scrape_hook(self, Router._obs_publish)
        router = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _proxy(self):
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length) if length else b""
                out = router.forward(
                    self.command, self.path, raw,
                    headers=dict(self.headers), sink=self)
                if out is None:
                    return   # SSE relay already wrote this socket
                code, body, extra = out
                extra = dict(extra or {})
                self.send_response(code)
                self.send_header("Content-Type", extra.pop(
                    "Content-Type", "application/json"))
                self.send_header("Content-Length", str(len(body)))
                for k, v in extra.items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            do_GET = _proxy
            do_POST = _proxy

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever, daemon=True,
                         name=f"router-{name}").start()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    @staticmethod
    def _ports(value) -> list[int]:
        if value is None:
            return []
        if isinstance(value, int):
            return [value]
        return [int(p) for p in value]

    def set_backends(self, default_port, canary_port=None,
                     canary_percent: int = 0) -> None:
        """Backends may be a single port or a list of replica ports."""
        with self._lock:
            self._default_ports = self._ports(default_port)
            self._canary_ports = self._ports(canary_port)
            self._canary_percent = max(0, min(100, int(canary_percent)))
            live = set(self._default_ports) | set(self._canary_ports)
            for p in live:
                self._circuits.setdefault(p, _Circuit(
                    self.failure_threshold, self.circuit_open_s,
                    self.circuit_open_cap_s, backend=str(p)))
            for p in list(self._circuits):
                if p not in live:   # replaced replicas take their state away
                    del self._circuits[p]

    def set_fault_injector(self, injector) -> None:
        self.fault_injector = injector

    def set_zones(self, zones: dict[str, Any] | None) -> None:
        """Assign backend ports to named zones (fleet chaos): while a
        `zone_outage` fault window targeting a zone is active, every
        port in it is unreachable from this router — the whole zone's
        circuits trip at once. A script target of None takes out every
        zone (full-fleet outage)."""
        with self._lock:
            self._zone_of = {}
            for zone, ports in (zones or {}).items():
                for p in self._ports(ports):
                    self._zone_of[p] = str(zone)

    def circuit_states(self) -> dict[int, str]:
        """Port -> breaker state (metrics / tests)."""
        now = time.monotonic()
        with self._lock:
            # report through admits() so an expired OPEN shows half_open
            out = {}
            for p, c in self._circuits.items():
                if c.state == OPEN and now >= c.open_until:
                    out[p] = HALF_OPEN
                else:
                    out[p] = c.state
            return out

    def _obs_publish(self) -> None:
        """Scrape hook body: refresh the router's concurrency gauge just
        before a /metrics render (circuit gauges update on transition,
        not here)."""
        obs_metrics.INFLIGHT.set(self.inflight, component="router")

    def health(self) -> dict[str, Any]:
        """The router's OWN liveness payload (served locally at
        /healthz, never proxied — an ingress answering for a backend
        would mask exactly the restarts fleet tooling is looking for).
        Top-level ``uptime_s`` + ``build`` mirror the ModelServer
        /healthz contract; backend state rides along as the breaker
        summary."""
        with self._lock:
            counts = {"total": self.total_count,
                      "canary": self.canary_count,
                      "breaker_rejected": self.breaker_rejected,
                      "stream_failovers": self.stream_failovers,
                      "stream_midfailures": self.stream_midfailures,
                      "affinity_hits": self.affinity_hits,
                      "affinity_failovers": self.affinity_failovers,
                      "inflight": self.inflight}
        return {"alive": True, "router": self.name,
                "uptime_s": round(time.monotonic() - self._start_mono, 3),
                "build": build_stamp(),
                "backends": {str(p): s
                             for p, s in self.circuit_states().items()},
                "counts": counts}

    def take_peak_inflight(self) -> int:
        """Peak concurrency since the last call (autoscaler signal)."""
        with self._lock:
            peak, self.peak_inflight = self.peak_inflight, self.inflight
            return peak

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    # -- routing --------------------------------------------------------------

    @staticmethod
    def _rotate(pool: list[int], cursor: int) -> list[int]:
        if not pool:
            return []
        i = cursor % len(pool)
        return pool[i:] + pool[:i]

    def _route(self, session_key: str | None = None
               ) -> tuple[list[int], bool, float | None, int | None]:
        """ONE client request's routing decision (the canary schedule
        advances exactly once per request, never per retry attempt):
        returns (candidates, is_canary, retry_in_s). Candidates are the
        ADMITTING backends of the scheduled pool — rendezvous-ranked by
        `session_key` when the request carries one (affinity: the top-
        ranked admitting replica is where this session's prefix KV
        lives), round-robin otherwise — followed by the other pool's
        admitting backends: a pool whose circuits are all open falls
        back to the healthy pool instead of serving 503s while capacity
        idles. Empty candidates with retry_in set means EVERY circuit is
        open; with retry_in None the service has no backends at all
        (scale-to-zero). The 4th element is the session's AFFINE port
        (rendezvous-first of the scheduled pool, admitting or not;
        None for keyless requests) — forward() scores affinity against
        the port that actually served."""
        now = time.monotonic()
        with self._lock:
            self._count += 1
            n, pct = self._count, self._canary_percent
            use_canary = (bool(self._canary_ports) and pct > 0
                          and (n * pct) // 100 > ((n - 1) * pct) // 100)
            prim = self._canary_ports if use_canary else self._default_ports
            sec = self._default_ports if use_canary else self._canary_ports
            if not prim and not sec:
                return [], use_canary, None, None
            affine = None
            if session_key is not None:
                order_p = _rendezvous_rank(prim, session_key)
                order_s = _rendezvous_rank(sec, session_key)
                affine = order_p[0] if order_p else None
            else:
                if use_canary:
                    self._rr_canary += 1
                    cursor = self._rr_canary
                else:
                    self._rr_default += 1
                    cursor = self._rr_default
                order_p = self._rotate(prim, cursor)
                order_s = self._rotate(sec, cursor)
            cand = [p for p in order_p if self._circuits[p].admits(now)]
            cand += [p for p in order_s
                     if p not in cand and self._circuits[p].admits(now)]
            if not cand:
                retry = min(self._circuits[p].retry_in(now)
                            for p in prim + sec)
                self.breaker_rejected += 1
                return [], use_canary, retry, affine
            return cand, use_canary, None, affine

    def _record(self, port: int, ok: bool) -> None:
        with self._lock:
            c = self._circuits.get(port)
            if c is None:
                return   # backend replaced while the request was in flight
            if ok:
                c.on_success()
            else:
                c.on_failure(time.monotonic())

    @staticmethod
    def _request_meta(headers: dict[str, str] | None,
                      body: bytes) -> tuple[str | None, bool]:
        """ONE bounded, best-effort body sniff per request →
        (session_key, wants_stream). Session key for affinity: the
        `X-Session-Key` header wins (explicit client intent), else the
        JSON body's `session` field, else the OpenAI `user` field (one
        end user = one conversation's worth of shared prefixes).
        wants_stream is the OpenAI `stream: true` flag — those requests
        get the stream-aware failover contract. A non-JSON or huge body
        routes keyless and buffered."""
        d = None
        if body and len(body) <= 1 << 20 and body.lstrip()[:1] == b"{":
            try:
                d = json.loads(body)
            except ValueError:
                d = None
            if not isinstance(d, dict):
                d = None
        wants_stream = bool(d and d.get("stream"))
        if headers:
            for k, v in headers.items():
                if k.lower() == "x-session-key" and v:
                    return str(v), wants_stream
        if d:
            for field in ("session", "user"):
                v = d.get(field)
                if isinstance(v, str) and v:
                    return v, wants_stream
        return None, wants_stream

    @staticmethod
    def _send_stream_headers(sink, status: int = 200) -> None:
        sink.send_response(status)
        sink.send_header("Content-Type", "text/event-stream")
        sink.send_header("Cache-Control", "no-cache")
        sink.send_header("Connection", "close")
        sink.end_headers()
        sink.close_connection = True

    def _stream_error_event(self, sink, port: int, delivered: int,
                            err: str | None) -> str:
        """The committed stream cannot be retried: emit the typed
        mid-stream error event (tokens_delivered = the journaled prefix
        length the client can resume from) and close it out. Always
        returns "failed": the BACKEND failed, and that verdict (what the
        breaker consumes) must not be laundered into "client_gone" just
        because the client also vanished before the event could be
        written."""
        with self._lock:
            self.stream_midfailures += 1
        payload = {"error": {
            "type": "mid_stream_failure",
            "tokens_delivered": delivered,
            "message": ("backend connection lost mid-stream"
                        + (f": {err}" if err else "")),
            "backend": port}}
        try:
            sink.wfile.write(b"data: " + json.dumps(payload).encode()
                             + b"\n\ndata: [DONE]\n\n")
            sink.wfile.flush()
        except OSError:
            pass   # client gone too; the backend verdict stands
        return "failed"

    def _relay_stream(self, sink, resp, port: int, headers_sent: bool
                      ) -> tuple[str, int, bool]:
        """Relay one SSE response onto the client socket, progressively.
        The 200 + SSE headers go out on the backend's first line, and
        COMMENT lines (`: keepalive` — a supervised backend mid-restart)
        relay immediately so the client connection never starves; but
        the stream only COMMITS on the first DATA event — a backend
        dying before any data event is retryable on the next replica
        ("retry": the client saw no events, and the next attempt simply
        continues the already-started SSE body without resending
        headers). After the first data event a backend failure becomes a
        typed `mid_stream_failure` error event carrying
        `tokens_delivered` followed by [DONE] ("failed"); a stream that
        relays through its [DONE] is "done". Returns (outcome,
        tokens_delivered, headers_sent)."""
        delivered = 0            # token events relayed to the client
        committed = False        # a data event reached the client
        saw_done = False
        err: str | None = None
        try:
            while True:
                try:
                    line = resp.readline()
                except OSError as e:
                    err = str(e)
                    break
                if not line:
                    break        # backend EOF
                if not headers_sent:
                    self._send_stream_headers(sink, resp.status)
                    headers_sent = True
                try:
                    sink.wfile.write(line)
                    sink.wfile.flush()
                except OSError:
                    return "client_gone", delivered, headers_sent
                if line.startswith(b"data: "):
                    committed = True
                    if line.strip() == b"data: [DONE]":
                        saw_done = True
                    elif b'"token_id"' in line:
                        delivered += 1
        except Exception as e:   # relay must never take the router down
            err = f"{type(e).__name__}: {e}"
        if saw_done:
            return "done", delivered, headers_sent
        if not committed:
            return "retry", 0, headers_sent
        return (self._stream_error_event(sink, port, delivered, err),
                delivered, headers_sent)

    def forward(self, method: str, path: str, body: bytes,
                headers: dict[str, str] | None = None, sink=None
                ) -> tuple[int, bytes, dict[str, str] | None] | None:
        """Proxy one request. Only CONNECT-phase failures (refused,
        injected partition/zone outage — the backend provably never saw
        the request) are retried on the next candidate backend: with one
        healthy replica left, the client sees 200, not the corpse's 502.
        For BUFFERED requests a failure AFTER the request was sent
        (timeout mid-generation, reset mid-response) is NOT retried —
        the backend may have executed it, and replaying a non-idempotent
        generation would silently duplicate it.

        STREAMING requests (`stream: true`, relayed progressively when
        `sink` — the client-side handler — is given) get stream-aware
        failover instead: any failure BEFORE the first token reached the
        client retries the same request on the next candidate (the
        client saw nothing, and supervised backends journal their side);
        a failure AFTER first token emits a typed `mid_stream_failure`
        event carrying `tokens_delivered` so the client can resume, then
        [DONE] — never a silently-truncated stream. Returns None when
        the response was relayed directly onto `sink`.

        Every failure feeds its backend's circuit. Requests carrying a
        session key route by rendezvous affinity (see _route) — the
        candidate order IS the failover order, so a pinned session
        degrades to the next healthy replica and re-pins by itself once
        the affine circuit closes."""
        if method == "GET" and path == "/metrics":
            # router-local: the unified registry in Prometheus text, the
            # same surface ModelServer serves (ISSUE 17 tentpole 2)
            return 200, render_metrics().encode(), \
                {"Content-Type": "text/plain; version=0.0.4"}
        if method == "GET" and path == "/healthz":
            return 200, json.dumps(self.health()).encode(), None
        self.last_request_time = time.time()
        session_key, wants_stream = self._request_meta(headers, body)
        wants_stream = wants_stream and sink is not None
        # trace id: adopt the client's X-Trace-Id, mint one otherwise —
        # the router is the edge, so every hop downstream (server →
        # supervisor → engine → roles/stages) shares this id
        trace = None
        if headers:
            for k, v in headers.items():
                if k.lower() == TRACE_HEADER.lower() and v:
                    trace = str(v)
                    break
        if trace is None:
            trace = new_trace_id()
        t_mono = time.monotonic()
        headers_sent = False   # SSE headers already on the client socket:
        # retries must continue the body, and errors must be SSE events
        candidates, is_canary, retry_in, affine = self._route(session_key)
        if not candidates and retry_in is not None:
            # every backend's circuit is open: schedule the retry instead
            # of hammering dead ports (503 + Retry-After, the chaos
            # tentpole's "all circuits open" contract)
            return 503, json.dumps(
                {"error": f"{self.name}: all backends unhealthy "
                          "(circuit open)"}).encode(), \
                {"Retry-After": str(max(1, math.ceil(retry_in)))}
        if not candidates and self.activator is not None:
            try:
                port = self._activate()
            except Exception as e:
                # a failing activator (model no longer loads) must
                # surface as an HTTP error, not a dropped connection
                # from a dead handler
                return 503, json.dumps(
                    {"error": f"{self.name}: activation failed: {e}"}
                ).encode(), None
            candidates = [port] if port is not None else []
        if not candidates:
            return 503, json.dumps(
                {"error": f"{self.name}: no ready backend"}
            ).encode(), None
        with self._lock:
            # counters are per client REQUEST, not per retry attempt —
            # the deterministic canary split and the autoscaler signal
            # must not drift during an outage
            self.total_count += 1
            if is_canary:
                self.canary_count += 1
            self.inflight += 1
            self.peak_inflight = max(self.peak_inflight, self.inflight)
        try:
            last_err: str | None = None
            hops = 0   # backends actually tried (failover depth)
            for port in candidates:
                hops += 1
                with self._lock:
                    c = self._circuits.get(port)
                    if c is not None:
                        c.on_attempt(time.monotonic())
                conn = http.client.HTTPConnection("127.0.0.1", port,
                                                  timeout=60)
                try:
                    inj = self.fault_injector
                    if inj is not None and inj.active(
                            "partition", target=str(port)) is not None:
                        raise ConnectionRefusedError(
                            "injected partition: router cannot "
                            f"reach :{port}")
                    if inj is not None and inj.active(
                            "zone_outage",
                            target=self._zone_of.get(port, "")) is not None:
                        raise ConnectionRefusedError(
                            "injected zone outage: router cannot reach "
                            f":{port} (zone "
                            f"{self._zone_of.get(port, '?')!r})")
                    conn.connect()
                except OSError as e:   # never reached the backend: retry
                    self._record(port, False)
                    last_err = str(e)
                    continue
                try:
                    conn.request(method, path, body=body or None,
                                 headers={"Content-Type":
                                          "application/json",
                                          TRACE_HEADER: trace})
                    resp = conn.getresponse()
                except OSError as e:
                    self._record(port, False)
                    if wants_stream:
                        # stream failover, pre-first-token: the client
                        # saw nothing — retry on the next candidate
                        with self._lock:
                            self.stream_failovers += 1
                        last_err = str(e)
                        conn.close()
                        continue
                    # buffered: the backend may have processed (part of)
                    # this — surface the failure, do NOT re-execute
                    return 502, json.dumps(
                        {"error": f"backend failed mid-request: {e}"}
                    ).encode(), None
                ctype = resp.getheader("Content-Type") or ""
                if wants_stream and ctype.startswith("text/event-stream"):
                    outcome, delivered, headers_sent = self._relay_stream(
                        sink, resp, port, headers_sent)
                    try:
                        conn.close()
                    except OSError:
                        pass
                    if outcome == "retry":
                        self._record(port, False)
                        with self._lock:
                            self.stream_failovers += 1
                        last_err = "backend died before first stream event"
                        continue
                    # "client_gone" is the CLIENT's doing — the backend
                    # was reachable and streaming, so it must not feed
                    # the breaker as a failure (three tab-closes would
                    # otherwise open a healthy backend's circuit)
                    self._record(port, outcome in ("done", "client_gone"))
                    if session_key is not None:
                        with self._lock:
                            if port == affine:
                                self.affinity_hits += 1
                            else:
                                self.affinity_failovers += 1
                    TRACER.record_span(
                        "router.relay", "http", trace, t_mono,
                        time.monotonic(), backend=port, hops=hops,
                        canary=is_canary, streamed=True, outcome=outcome,
                        tokens_delivered=delivered)
                    return None   # the socket is already written
                if headers_sent:
                    # the SSE body already started but this retry
                    # answered with a NON-stream response (e.g. a busy
                    # replica's 429/503 JSON): a JSON body cannot follow
                    # SSE headers, but nothing is committed (no data
                    # event reached the client) — keep trying the
                    # remaining candidates. The response itself was a
                    # transport SUCCESS, so it must not feed the breaker
                    # (a load spike must not open a healthy circuit);
                    # exhaustion falls through to the terminal error
                    # event below.
                    try:
                        resp.read()
                        conn.close()
                    except OSError:
                        pass
                    self._record(port, True)
                    last_err = (f"retry answered non-stream HTTP "
                                f"{resp.status}")
                    continue
                try:
                    data = resp.read()
                    conn.close()
                except OSError as e:
                    self._record(port, False)
                    if wants_stream:
                        # an SSE request answered with a NON-stream body
                        # (an error JSON) whose read failed before any
                        # byte reached the client: still safe to retry
                        with self._lock:
                            self.stream_failovers += 1
                        last_err = str(e)
                        continue
                    return 502, json.dumps(
                        {"error": f"backend failed mid-request: {e}"}
                    ).encode(), None
                self._record(port, True)
                if session_key is not None:
                    # scored on the port that actually SERVED (a
                    # connect-retry onto a lower-ranked replica is a
                    # failover even though routing ranked it)
                    with self._lock:
                        if port == affine:
                            self.affinity_hits += 1
                        else:
                            self.affinity_failovers += 1
                TRACER.record_span(
                    "router.relay", "http", trace, t_mono,
                    time.monotonic(), backend=port, hops=hops,
                    canary=is_canary, streamed=False, status=resp.status)
                return resp.status, data, None
            if headers_sent:
                # candidates exhausted AFTER the SSE body started: the
                # client must get a terminal event, not a dropped socket
                self._stream_error_event(
                    sink, 0, 0, f"all backends unreachable: {last_err}")
                return None
            TRACER.record_span(
                "router.relay", "http", trace, t_mono, time.monotonic(),
                hops=hops, canary=is_canary, outcome="unreachable",
                error=last_err)
            return 502, json.dumps(
                {"error": f"backend unreachable: {last_err}"}
            ).encode(), None
        finally:
            with self._lock:
                self.inflight -= 1

    def _activate(self) -> int | None:
        """Scale-from-zero: ask the controller to start the backend, then
        wait for it (the Knative activator hold-and-release)."""
        deadline = time.monotonic() + self.activation_timeout
        port = self.activator()
        while port is None and time.monotonic() < deadline:
            time.sleep(0.05)
            port = self.activator()
        if port is not None:
            with self._lock:
                self._default_ports = self._ports(port)
                self._circuits.setdefault(port, _Circuit(
                    self.failure_threshold, self.circuit_open_s,
                    self.circuit_open_cap_s, backend=str(port)))
        return port
