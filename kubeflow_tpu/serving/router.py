"""Traffic router — the Istio ingress + Knative activator analog (SURVEY.md
§3.5: "Istio ingress ⇉ Knative activator/queue-proxy (concurrency,
scale-from-zero)").

One Router per InferenceService: an HTTP reverse proxy that
  - splits traffic between the default and canary backends by percentage
    (deterministic modular schedule, so a 20% canary gets exactly every
    5th request — testable, no RNG flakes);
  - on scale-to-zero services, calls the activator hook to spin the backend
    up on first request and records last-request time for idle scale-down.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable


class Router:
    def __init__(self, name: str, port: int = 0,
                 activator: Callable[[], int | None] | None = None,
                 activation_timeout: float = 30.0):
        self.name = name
        self.activator = activator
        self.activation_timeout = activation_timeout
        self._lock = threading.Lock()
        self._default_ports: list[int] = []
        self._canary_ports: list[int] = []
        self._canary_percent = 0
        self._count = 0
        # separate round-robin cursors per pool: a shared cursor plus a
        # deterministic canary schedule can phase-lock and starve a replica
        self._rr_default = 0
        self._rr_canary = 0
        self.canary_count = 0
        self.total_count = 0
        self.last_request_time: float = 0.0
        # concurrency tracking for the autoscaler (Knative queue-proxy
        # reports concurrency; here the router IS the queue-proxy)
        self.inflight = 0
        self.peak_inflight = 0
        router = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _proxy(self):
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length) if length else b""
                code, body = router.forward(self.command, self.path, raw)
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            do_GET = _proxy
            do_POST = _proxy

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever, daemon=True,
                         name=f"router-{name}").start()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    @staticmethod
    def _ports(value) -> list[int]:
        if value is None:
            return []
        if isinstance(value, int):
            return [value]
        return [int(p) for p in value]

    def set_backends(self, default_port, canary_port=None,
                     canary_percent: int = 0) -> None:
        """Backends may be a single port or a list of replica ports."""
        with self._lock:
            self._default_ports = self._ports(default_port)
            self._canary_ports = self._ports(canary_port)
            self._canary_percent = max(0, min(100, int(canary_percent)))

    def take_peak_inflight(self) -> int:
        """Peak concurrency since the last call (autoscaler signal)."""
        with self._lock:
            peak, self.peak_inflight = self.peak_inflight, self.inflight
            return peak

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    # -- routing --------------------------------------------------------------

    def _pick(self) -> tuple[int | None, bool]:
        with self._lock:
            self._count += 1
            n, pct = self._count, self._canary_percent
            use_canary = (bool(self._canary_ports) and pct > 0
                          and (n * pct) // 100 > ((n - 1) * pct) // 100)
            pool = self._canary_ports if use_canary else self._default_ports
            if not pool:
                return None, use_canary
            if use_canary:
                self._rr_canary += 1
                return pool[self._rr_canary % len(pool)], True
            self._rr_default += 1
            return pool[self._rr_default % len(pool)], False

    def forward(self, method: str, path: str, body: bytes
                ) -> tuple[int, bytes]:
        self.last_request_time = time.time()
        port, is_canary = self._pick()
        if port is None and self.activator is not None:
            try:
                port = self._activate()
            except Exception as e:
                # a failing activator (model no longer loads) must surface as
                # an HTTP error, not a dropped connection from a dead handler
                return 503, json.dumps(
                    {"error": f"{self.name}: activation failed: {e}"}).encode()
        if port is None:
            return 503, json.dumps(
                {"error": f"{self.name}: no ready backend"}).encode()
        with self._lock:
            self.total_count += 1
            if is_canary:
                self.canary_count += 1
            self.inflight += 1
            self.peak_inflight = max(self.peak_inflight, self.inflight)
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
            conn.request(method, path, body=body or None,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = resp.read()
            conn.close()
            return resp.status, data
        except OSError as e:
            return 502, json.dumps(
                {"error": f"backend unreachable: {e}"}).encode()
        finally:
            with self._lock:
                self.inflight -= 1

    def _activate(self) -> int | None:
        """Scale-from-zero: ask the controller to start the backend, then
        wait for it (the Knative activator hold-and-release)."""
        deadline = time.monotonic() + self.activation_timeout
        port = self.activator()
        while port is None and time.monotonic() < deadline:
            time.sleep(0.05)
            port = self.activator()
        if port is not None:
            with self._lock:
                self._default_ports = self._ports(port)
        return port
