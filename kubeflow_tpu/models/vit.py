"""Vision Transformer — the image-classification transformer family
(SURVEY.md §2.2/L7: the reference's users train ViTs through TFJob/
PyTorchJob; here it is a built-in model on the same pjit/mesh stack as
llama).

TPU-first like the rest of `models/`: pure-functional param pytrees with
logical sharding axes, layers stacked for ``lax.scan``, bf16 compute with
fp32 statistics, bidirectional flash attention (the same kernel llama
uses, ``causal=False``), patchify as one strided conv (a single MXU-friendly
matmul per image).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from kubeflow_tpu.ops.flash_attention import flash_attention
from kubeflow_tpu.ops.norms import rms_norm

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 32
    patch_size: int = 4
    in_channels: int = 3
    n_classes: int = 10
    d_model: int = 192
    n_layers: int = 6
    n_heads: int = 3
    d_ff: int = 768
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    norm_eps: float = 1e-6
    remat: bool = False
    attention_impl: str = "flash"   # flash | xla

    def __post_init__(self):
        if self.image_size % self.patch_size:
            raise ValueError("patch_size must divide image_size")
        if self.d_model % self.n_heads:
            raise ValueError("n_heads must divide d_model")

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2


def init(rng: jax.Array, cfg: ViTConfig) -> Params:
    L, d, f = cfg.n_layers, cfg.d_model, cfg.d_ff
    patch_dim = cfg.patch_size * cfg.patch_size * cfg.in_channels
    ks = jax.random.split(rng, 8)

    def norm(key, *shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (2.0 / fan_in) ** 0.5).astype(cfg.param_dtype)

    return {
        "patch_embed": {"w": norm(ks[0], patch_dim, d, fan_in=patch_dim),
                        "b": jnp.zeros((d,), cfg.param_dtype)},
        "pos_embed": 0.02 * jax.random.normal(
            ks[1], (cfg.n_patches + 1, d), cfg.param_dtype),
        "cls": jnp.zeros((d,), cfg.param_dtype),
        "layers": {
            "attn_norm": jnp.ones((L, d), cfg.param_dtype),
            "wq": norm(ks[2], L, d, d, fan_in=d),
            "wk": norm(ks[3], L, d, d, fan_in=d),
            "wv": norm(ks[4], L, d, d, fan_in=d),
            "wo": norm(ks[5], L, d, d, fan_in=d),
            "mlp_norm": jnp.ones((L, d), cfg.param_dtype),
            "w_up": norm(ks[6], L, d, f, fan_in=d),
            "w_down": norm(ks[7], L, f, d, fan_in=f),
        },
        "final_norm": jnp.ones((d,), cfg.param_dtype),
        "head": {"w": jnp.zeros((d, cfg.n_classes), cfg.param_dtype),
                 "b": jnp.zeros((cfg.n_classes,), cfg.param_dtype)},
    }


def logical_axes(cfg: ViTConfig) -> Params:
    return {
        "patch_embed": {"w": (None, "embed"), "b": ("embed",)},
        "pos_embed": (None, "embed"),
        "cls": ("embed",),
        "layers": {
            # leading [L] dim tagged "layers" like llama/bert: stage-sharded
            # slabs under a pipeline mesh instead of full replication
            "attn_norm": ("layers", None),
            "wq": ("layers", "embed", "heads"),
            "wk": ("layers", "embed", "heads"),
            "wv": ("layers", "embed", "heads"),
            "wo": ("layers", "heads", "embed"),
            "mlp_norm": ("layers", None),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
        },
        "final_norm": (None,),
        "head": {"w": ("embed", None), "b": (None,)},
    }


def _patchify(images: jax.Array, cfg: ViTConfig) -> jax.Array:
    """[B,H,W,C] -> [B, n_patches, patch_dim] (reshape-only, no conv)."""
    b, h, w, c = images.shape
    p = cfg.patch_size
    x = images.reshape(b, h // p, p, w // p, p, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, (h // p) * (w // p), p * p * c)


def _layer_body(cfg: ViTConfig, x: jax.Array, layer: Params) -> jax.Array:
    b, s, d = x.shape
    nh, hd = cfg.n_heads, cfg.d_model // cfg.n_heads
    h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    q = (h @ layer["wq"].astype(cfg.dtype)).reshape(b, s, nh, hd)
    k = (h @ layer["wk"].astype(cfg.dtype)).reshape(b, s, nh, hd)
    v = (h @ layer["wv"].astype(cfg.dtype)).reshape(b, s, nh, hd)
    if cfg.attention_impl == "flash":
        out = flash_attention(q, k, v, causal=False)
    else:
        scale = hd ** -0.5
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        probs = jax.nn.softmax(logits.astype(jnp.float32), -1)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(cfg.dtype), v)
    x = x + out.reshape(b, s, d) @ layer["wo"].astype(cfg.dtype)
    h = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
    h = jax.nn.gelu(h @ layer["w_up"].astype(cfg.dtype))
    return x + h @ layer["w_down"].astype(cfg.dtype)


def apply(params: Params, images: jax.Array, cfg: ViTConfig) -> jax.Array:
    """[B, H, W, C] float images -> [B, n_classes] fp32 logits."""
    b = images.shape[0]
    x = _patchify(images.astype(cfg.dtype), cfg)
    x = x @ params["patch_embed"]["w"].astype(cfg.dtype) \
        + params["patch_embed"]["b"].astype(cfg.dtype)
    cls = jnp.broadcast_to(params["cls"].astype(cfg.dtype),
                           (b, 1, cfg.d_model))
    x = jnp.concatenate([cls, x], axis=1)
    x = x + params["pos_embed"].astype(cfg.dtype)

    body = partial(_layer_body, cfg)
    if cfg.remat:
        body = jax.checkpoint(body)

    def scan_body(carry, layer):
        return body(carry, layer), None

    x, _ = jax.lax.scan(scan_body, x, params["layers"])
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    cls_out = x[:, 0].astype(jnp.float32)
    return cls_out @ params["head"]["w"].astype(jnp.float32) \
        + params["head"]["b"]


def loss_fn(params: Params, batch: dict[str, jax.Array], cfg: ViTConfig):
    logits = apply(params, batch["image"], cfg)
    labels = batch["label"]
    logp = jax.nn.log_softmax(logits)
    loss = -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))
    acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
    return loss, {"loss": loss, "accuracy": acc}
