"""LoRA fine-tuning for the llama family (PEFT parity).

The reference platform fine-tunes via user containers (PEFT/HF inside a
PyTorchJob — SURVEY.md L7); newer kubeflow trainer ships LoRA trainers as
first-class blueprints. Here LoRA is a registered model family
(``model: llama_lora`` in a JAXJob) so every platform surface — trainer,
HPO sweeps over rank/alpha, checkpointing, serving export — composes with
it unchanged.

Design (TPU-first):
  - params = {"base": <frozen llama tree>, "lora": {target: {"a", "b"}}} —
    the base rides under ``jax.lax.stop_gradient``, so its backward pass is
    never computed; the optimizer additionally freezes it structurally
    (OptimizerConfig.trainable_prefix="lora"), so Adam moments exist ONLY
    for adapter leaves — the memory win that makes 8B fine-tune fit where
    full fine-tune would not.
  - the merged weight W + (alpha/r)·A@B is materialized per step as a
    stacked-layer einsum ("ldr,lro->ldo") and fed to the unmodified llama
    forward: one extra O(params·r/d) matmul, zero change to the hot path,
    and every attention mode (flash/ring/ulysses) plus the pipeline/TP/FSDP
    shardings keep working because the merged tree IS a llama tree.
  - export: ``merge(params, cfg)`` returns plain llama params for the
    serving engine; ``adapter_only(params)`` is the checkpoint-sized
    artifact (rank·(d_in+d_out) per target per layer).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from kubeflow_tpu.models import llama


@dataclasses.dataclass(frozen=True)
class LoraLlamaConfig:
    rank: int = 8
    alpha: float = 16.0
    # any stacked-layer matmul leaf of the llama tree can be a target
    targets: tuple = ("wq", "wk", "wv", "wo")
    # base-model fields (LlamaConfig kwargs); a JAXJob spec writes
    # model_overrides: {rank: 8, llama: {d_model: ..., n_layers: ...}}
    llama: dict = dataclasses.field(default_factory=dict)
    # optional pretrained base: an HF safetensors dir or an orbax params
    # checkpoint (the realistic fine-tune path); None = random init (tests)
    base_checkpoint: str | None = None

    def __post_init__(self):
        if self.rank < 1:
            raise ValueError("lora rank must be >= 1")
        known = set(llama.QUANT_LEAVES)
        bad = set(self.targets) - known
        if bad:
            raise ValueError(f"unknown lora targets {sorted(bad)}; "
                             f"known: {sorted(known)}")
        # build (and cache) the base config NOW: a bad key in the llama
        # dict must fail at config construction, not as a TypeError from
        # some later arbitrary attribute read via __getattr__
        try:
            base = llama.LlamaConfig(**self.llama)
        except TypeError as e:
            raise ValueError(f"bad llama base-config fields: {e}") from None
        object.__setattr__(self, "_base_cfg", base)

    @property
    def base_cfg(self) -> llama.LlamaConfig:
        return self._base_cfg

    # the trainer logs MFU against the model config; delegate the fields
    # it reads so llama_lora quacks like its base where it matters
    def __getattr__(self, name: str) -> Any:
        if name.startswith("_"):   # e.g. _base_cfg before __post_init__
            raise AttributeError(name)
        return getattr(self.base_cfg, name)


def _load_base(cfg: LoraLlamaConfig) -> llama.Params:
    path = cfg.base_checkpoint
    if llama.is_hf_checkpoint(path):
        params, _ = llama.load_hf(path, cfg.base_cfg)
        return params
    from kubeflow_tpu.training.checkpoint import restore_params

    abstract = jax.eval_shape(
        lambda: llama.init(jax.random.key(0), cfg.base_cfg))
    return restore_params(path, abstract)


def init(rng: jax.Array, cfg: LoraLlamaConfig) -> llama.Params:
    bcfg = cfg.base_cfg
    if cfg.base_checkpoint:
        base = _load_base(cfg)
    else:
        base = llama.init(rng, bcfg)
    pd = bcfg.param_dtype
    adapters = {}
    for i, t in enumerate(cfg.targets):
        leaf = base["layers"][t]  # [L, d_in, d_out]
        _, d_in, d_out = leaf.shape
        k = jax.random.fold_in(rng, 1000 + i)
        adapters[t] = {
            # standard LoRA init: a ~ N(0, 1/d_in), b = 0 — the merged
            # model equals the base exactly at step 0
            "a": (jax.random.normal(k, (bcfg.n_layers, d_in, cfg.rank),
                                    jnp.float32) / (d_in ** 0.5)).astype(pd),
            "b": jnp.zeros((bcfg.n_layers, cfg.rank, d_out), pd),
        }
    return {"base": base, "lora": adapters}


def merge(params: llama.Params, cfg: LoraLlamaConfig,
          *, stop_base_gradient: bool = True) -> llama.Params:
    """base + (alpha/rank)·A@B for every target — a plain llama tree (feed
    it to llama.apply, the serving engine, or quantize_params)."""
    base = (jax.tree.map(jax.lax.stop_gradient, params["base"])
            if stop_base_gradient else params["base"])
    scale = cfg.alpha / cfg.rank
    layers = dict(base["layers"])
    for t in cfg.targets:
        ab = params["lora"][t]
        delta = jnp.einsum("ldr,lro->ldo", ab["a"].astype(jnp.float32),
                           ab["b"].astype(jnp.float32)) * scale
        layers[t] = (base["layers"][t]
                     + delta.astype(base["layers"][t].dtype))
    return {**base, "layers": layers}


def adapter_only(params: llama.Params) -> llama.Params:
    """The checkpoint-sized artifact: just the adapter leaves."""
    return {"lora": params["lora"]}


def apply(params, tokens, cfg: LoraLlamaConfig, **kw):
    return llama.apply(merge(params, cfg), tokens, cfg.base_cfg, **kw)


def loss_fn(params, batch, cfg: LoraLlamaConfig):
    return llama.loss_fn(merge(params, cfg), batch, cfg.base_cfg)


def logical_axes(cfg: LoraLlamaConfig) -> llama.Params:
    """Adapters shard like their target's matching dimension: a keeps the
    input axis (rank replicated), b keeps the output axis — under TP/FSDP
    the A@B einsum then contracts locally exactly like the base matmul."""
    base = llama.logical_axes(cfg.base_cfg)
    lora = {}
    for t in cfg.targets:
        _, in_ax, out_ax = base["layers"][t]  # ("layers", in, out)
        lora[t] = {"a": ("layers", in_ax, None),
                   "b": ("layers", None, out_ax)}
    return {"base": base, "lora": lora}
