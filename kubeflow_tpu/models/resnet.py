"""ResNet (v1.5 bottleneck) for the Katib HPO sweep workload.

BASELINE.json config #4: "Katib Bayesian HPO, 32 trials over ResNet-50/
ImageNet JAXJob". Design choice: GroupNorm instead of BatchNorm — identical
accuracy regime for this workload class, but stateless, which keeps the
framework's uniform functional model interface (params -> logits) and avoids
cross-device batch-stat sync entirely (BN running stats are the one piece of
torch-style mutable state that maps poorly onto pjit).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: tuple[int, ...] = (3, 4, 6, 3)  # resnet-50
    width: int = 64
    n_classes: int = 1000
    groups: int = 32
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    # synthetic-data pipeline knob (training/data.for_model): convs are
    # size-agnostic, so this only picks the image resolution jobs train
    # on — 64 keeps tests/toy sweeps fast, 224 is the true-geometry
    # ResNet-50 setting (scripts/baseline_sweep.py --resnet50)
    image_size: int = 64

    @staticmethod
    def resnet50(n_classes: int = 1000) -> "ResNetConfig":
        return ResNetConfig(stage_sizes=(3, 4, 6, 3), n_classes=n_classes)

    @staticmethod
    def tiny(n_classes: int = 10) -> "ResNetConfig":
        return ResNetConfig(stage_sizes=(1, 1), width=16, n_classes=n_classes,
                            groups=4)


def _conv_init(key, shape):
    fan_in = shape[0] * shape[1] * shape[2]
    return jax.random.normal(key, shape, jnp.float32) * (2.0 / fan_in) ** 0.5


def _group_norm(x, w, b, groups, eps=1e-5):
    n, h, wd, c = x.shape
    g = min(groups, c)
    while c % g:
        g -= 1
    xf = x.astype(jnp.float32).reshape(n, h, wd, g, c // g)
    mean = xf.mean(axis=(1, 2, 4), keepdims=True)
    var = xf.var(axis=(1, 2, 4), keepdims=True)
    xf = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (xf.reshape(n, h, wd, c) * w + b).astype(x.dtype)


def _conv(x, w, stride=1):
    return jax.lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def init(rng: jax.Array, cfg: ResNetConfig) -> Params:
    keys = iter(jax.random.split(rng, 256))
    pd = cfg.param_dtype

    def norm(c):
        return {"w": jnp.ones((c,), pd), "b": jnp.zeros((c,), pd)}

    params: Params = {
        "stem": {"w": _conv_init(next(keys), (7, 7, 3, cfg.width)).astype(pd),
                 "norm": norm(cfg.width)},
        "stages": [],
    }
    c_in = cfg.width
    for i, n_blocks in enumerate(cfg.stage_sizes):
        c_mid = cfg.width * (2**i)
        c_out = c_mid * 4
        stage = []
        for j in range(n_blocks):
            block = {
                "conv1": {"w": _conv_init(next(keys), (1, 1, c_in, c_mid)).astype(pd),
                          "norm": norm(c_mid)},
                "conv2": {"w": _conv_init(next(keys), (3, 3, c_mid, c_mid)).astype(pd),
                          "norm": norm(c_mid)},
                "conv3": {"w": _conv_init(next(keys), (1, 1, c_mid, c_out)).astype(pd),
                          "norm": norm(c_out)},
            }
            if j == 0:
                block["proj"] = {
                    "w": _conv_init(next(keys), (1, 1, c_in, c_out)).astype(pd),
                    "norm": norm(c_out)}
            stage.append(block)
            c_in = c_out
        params["stages"].append(stage)
    params["fc"] = {
        "w": (jax.random.normal(next(keys), (c_in, cfg.n_classes), jnp.float32)
              * 0.01).astype(pd),
        "b": jnp.zeros((cfg.n_classes,), pd),
    }
    return params


def logical_axes(cfg: ResNetConfig) -> Params:
    def conv_ax():
        return {"w": (None, None, "conv_in", "conv_out"),
                "norm": {"w": (None,), "b": (None,)}}

    axes: Params = {"stem": conv_ax(), "stages": []}
    for n_blocks in cfg.stage_sizes:
        stage = []
        for j in range(n_blocks):
            block = {"conv1": conv_ax(), "conv2": conv_ax(), "conv3": conv_ax()}
            if j == 0:
                block["proj"] = conv_ax()
            stage.append(block)
        axes["stages"].append(stage)
    axes["fc"] = {"w": ("embed", None), "b": (None,)}
    return axes


def _bottleneck(x, block, cfg, stride):
    g = cfg.groups
    residual = x
    h = _conv(x, block["conv1"]["w"])
    h = jax.nn.relu(_group_norm(h, block["conv1"]["norm"]["w"],
                                block["conv1"]["norm"]["b"], g))
    h = _conv(h, block["conv2"]["w"], stride)
    h = jax.nn.relu(_group_norm(h, block["conv2"]["norm"]["w"],
                                block["conv2"]["norm"]["b"], g))
    h = _conv(h, block["conv3"]["w"])
    h = _group_norm(h, block["conv3"]["norm"]["w"], block["conv3"]["norm"]["b"], g)
    if "proj" in block:
        residual = _conv(x, block["proj"]["w"], stride)
        residual = _group_norm(residual, block["proj"]["norm"]["w"],
                               block["proj"]["norm"]["b"], g)
    return jax.nn.relu(h + residual)


def apply(params: Params, images: jax.Array, cfg: ResNetConfig) -> jax.Array:
    """images [B,H,W,3] -> logits [B, n_classes]."""
    x = images.astype(cfg.dtype)
    x = _conv(x, params["stem"]["w"], stride=2)
    x = jax.nn.relu(_group_norm(x, params["stem"]["norm"]["w"],
                                params["stem"]["norm"]["b"], cfg.groups))
    x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max,
                              (1, 3, 3, 1), (1, 2, 2, 1), "SAME")
    for i, stage in enumerate(params["stages"]):
        for j, block in enumerate(stage):
            stride = 2 if (i > 0 and j == 0) else 1
            x = _bottleneck(x, block, cfg, stride)
    x = jnp.mean(x, axis=(1, 2))
    logits = x @ params["fc"]["w"].astype(cfg.dtype) + params["fc"]["b"].astype(cfg.dtype)
    return logits.astype(jnp.float32)


def loss_fn(params: Params, batch: dict[str, jax.Array], cfg: ResNetConfig):
    logits = apply(params, batch["image"], cfg)
    labels = batch["label"]
    logp = jax.nn.log_softmax(logits)
    loss = -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))
    acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
    return loss, {"loss": loss, "accuracy": acc}
