"""Mixtral-style MoE transformer: llama attention blocks + top-k-routed
SwiGLU experts, expert-parallel over the mesh `expert` axis.

The reference platform orchestrates MoE only as opaque user containers
(SURVEY.md §2.2: expert parallelism "user code only"); here it is a
first-class model family. All expert weights are stacked [L, E, ...] so the
layer scan and the expert sharding compose; GSPMD turns the dispatch einsums
into the expert all-to-all (see ops/moe.py).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from kubeflow_tpu.models import llama
from kubeflow_tpu.ops.moe import MoEArgs, moe_mlp
from kubeflow_tpu.ops.norms import rms_norm

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class MoELlamaConfig(llama.LlamaConfig):
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_coef: float = 0.01
    router_z_coef: float = 1e-3

    @property
    def moe_args(self) -> MoEArgs:
        return MoEArgs(self.n_experts, self.top_k, self.capacity_factor,
                       self.aux_loss_coef, self.router_z_coef)

    @staticmethod
    def mixtral_8x7b() -> "MoELlamaConfig":
        return MoELlamaConfig(vocab_size=32000, d_model=4096, n_layers=32,
                              n_heads=32, n_kv_heads=8, d_ff=14336,
                              max_seq_len=32768, rope_theta=1e6,
                              n_experts=8, top_k=2)

    @staticmethod
    def tiny(vocab_size: int = 512) -> "MoELlamaConfig":
        return MoELlamaConfig(vocab_size=vocab_size, d_model=64, n_layers=2,
                              n_heads=8, n_kv_heads=4, d_ff=96,
                              max_seq_len=128, rope_theta=10000.0,
                              n_experts=4, top_k=2)


def init(rng: jax.Array, cfg: MoELlamaConfig) -> Params:
    params = llama.init(rng, cfg)
    pd = cfg.param_dtype
    d, f, L, E = cfg.d_model, cfg.d_ff, cfg.n_layers, cfg.n_experts
    keys = jax.random.split(jax.random.fold_in(rng, 101), 4)

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                / (fan_in ** 0.5)).astype(pd)

    layers = params["layers"]
    for name in ("w_gate", "w_up", "w_down"):
        del layers[name]
    layers["router"] = dense(keys[0], (L, d, E), d)
    layers["w_gate"] = dense(keys[1], (L, E, d, f), d)
    layers["w_up"] = dense(keys[2], (L, E, d, f), d)
    layers["w_down"] = dense(keys[3], (L, E, f, d), f)
    return params


def logical_axes(cfg: MoELlamaConfig) -> Params:
    axes = llama.logical_axes(cfg)
    axes["layers"]["router"] = ("layers", "embed", None)
    axes["layers"]["w_gate"] = ("layers", "expert", "embed", "mlp")
    axes["layers"]["w_up"] = ("layers", "expert", "embed", "mlp")
    axes["layers"]["w_down"] = ("layers", "expert", "mlp", "embed")
    return axes


def _layer_body(cfg: MoELlamaConfig, carry, layer, positions, segment_ids):
    x, aux = carry
    x = llama._attention(cfg, x, layer, positions, segment_ids)
    h = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
    out, layer_aux = moe_mlp(h, layer["router"], layer["w_gate"],
                             layer["w_up"], layer["w_down"], cfg.moe_args,
                             dtype=cfg.dtype)
    return (x + out, aux + layer_aux), None


def apply(
    params: Params,
    tokens: jax.Array,
    cfg: MoELlamaConfig,
    *,
    positions: jax.Array | None = None,
    segment_ids: jax.Array | None = None,
    return_aux: bool = False,
):
    """[B, S] int tokens -> [B, S, vocab] fp32 logits (+ router aux loss)."""
    b, s = tokens.shape
    if positions is None:
        positions = jnp.arange(s)
    x = params["embed"].astype(cfg.dtype)[tokens]

    body = partial(_layer_body, cfg, positions=positions,
                   segment_ids=segment_ids)
    if cfg.remat:
        policy = {
            "minimal": jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
            "full": jax.checkpoint_policies.nothing_saveable,
            "none": jax.checkpoint_policies.everything_saveable,
        }[cfg.remat_policy]
        body = jax.checkpoint(body, policy=policy)
    if cfg.scan_layers:
        (x, aux), _ = jax.lax.scan(body, (x, jnp.zeros((), jnp.float32)),
                                   params["layers"])
    else:
        carry = (x, jnp.zeros((), jnp.float32))
        for i in range(cfg.n_layers):
            layer = jax.tree.map(lambda p: p[i], params["layers"])
            carry, _ = body(carry, layer)
        x, aux = carry

    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum("bsd,dv->bsv", x, params["lm_head"].astype(cfg.dtype),
                        preferred_element_type=jnp.float32)
    return (logits, aux) if return_aux else logits


def loss_fn(params: Params, batch: dict[str, jax.Array], cfg: MoELlamaConfig):
    """Next-token cross-entropy + router load-balance aux loss."""
    tokens = batch["tokens"]
    logits, aux = apply(params, tokens, cfg,
                        positions=jnp.arange(tokens.shape[1]),
                        segment_ids=batch.get("segment_ids"),
                        return_aux=True)
    logits = logits[:, :-1]
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    token_loss = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    mask = batch.get("loss_mask")
    mask = jnp.ones_like(token_loss) if mask is None else mask[:, 1:]
    total = jnp.sum(token_loss * mask)
    denom = jnp.maximum(jnp.sum(mask), 1.0)
    ce = total / denom
    return ce + aux, {"loss": ce, "aux_loss": aux, "tokens": jnp.sum(mask)}


def flops_per_token(cfg: MoELlamaConfig, seq_len: int) -> float:
    """Training FLOPs/token counting only ACTIVE experts (top_k of E)."""
    d, f, hd = cfg.d_model, cfg.d_ff, cfg.head_dim
    nh, nkv, L = cfg.n_heads, cfg.n_kv_heads, cfg.n_layers
    attn_params = L * (d * nh * hd + 2 * d * nkv * hd + nh * hd * d)
    moe_params = L * (cfg.top_k * 3 * d * f + d * cfg.n_experts)
    embed_params = cfg.vocab_size * d
    attn_flops = 12 * L * nh * hd * seq_len
    return 6.0 * (attn_params + moe_params + embed_params) + attn_flops
