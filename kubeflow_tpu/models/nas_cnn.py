"""NAS-searchable CNN + DARTS-style supernet — Katib's NAS capability
(SURVEY.md §2.3 suggestion row: ENAS/DARTS services ⊘ katib
pkg/suggestion/v1beta1/nas) rebuilt TPU-first.

Two search modes, matching how Katib's two NAS algorithms divide the work:

1. **Trial-based search** (the ENAS-experiment shape): `NasCnnConfig.ops`
   picks one operation per layer from OP_NAMES; each architecture is a
   normal model any HPO algorithm can drive through the Experiment
   controller (`nasConfig` -> categorical parameters, hpo/nas.py). Every
   trial is an ordinary gang-scheduled training job.

2. **Differentiable search (DARTS)**: `darts_init`/`darts_loss_fn` build a
   supernet where every layer runs ALL candidate ops and mixes them with a
   softmax over architecture logits alpha — one jitted program, all-ops
   compute batched for the MXU (no data-dependent branching), exactly how
   differentiable NAS should map onto XLA. `derive` reads off the argmax
   architecture for retraining as mode 1.

Ops are shape-preserving NHWC blocks so any op sequence composes; spatial
reduction happens at fixed stride points like the DARTS macro skeleton.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

Params = dict[str, Any]

OP_NAMES: tuple[str, ...] = ("conv3", "conv5", "sep3", "maxpool", "avgpool",
                             "identity")


@dataclasses.dataclass(frozen=True)
class NasCnnConfig:
    n_classes: int = 10
    channels: int = 16
    image_size: int = 16
    in_channels: int = 3
    ops: tuple[str, ...] = ("conv3", "conv3", "conv3")  # one per layer
    reduce_every: int = 2      # stride-2 pool after every k-th layer
    dtype: Any = jnp.float32

    def __post_init__(self):
        for op in self.ops:
            if op not in OP_NAMES:
                raise ValueError(f"unknown op {op!r}; known: {OP_NAMES}")


def _he(key, shape, fan_in):
    return jax.random.normal(key, shape, jnp.float32) * (2.0 / fan_in) ** 0.5


def _op_params(key, op: str, c: int) -> Params:
    """Every op gets its full parameter set so supernet layers can hold all
    ops at once; parameter-free ops get an empty dict."""
    if op == "conv3":
        return {"w": _he(key, (3, 3, c, c), 9 * c), "b": jnp.zeros((c,))}
    if op == "conv5":
        return {"w": _he(key, (5, 5, c, c), 25 * c), "b": jnp.zeros((c,))}
    if op == "sep3":
        k1, k2 = jax.random.split(key)
        # depthwise HWIO with feature_group_count=C: (H, W, 1, C)
        return {"dw": _he(k1, (3, 3, 1, c), 9),
                "pw": _he(k2, (1, 1, c, c), c), "b": jnp.zeros((c,))}
    return {}  # maxpool / avgpool / identity


def _apply_op(op: str, p: Params, x: jax.Array) -> jax.Array:
    dn = ("NHWC", "HWIO", "NHWC")
    if op in ("conv3", "conv5"):
        y = jax.lax.conv_general_dilated(x, p["w"], (1, 1), "SAME",
                                         dimension_numbers=dn)
        return jax.nn.relu(y + p["b"])
    if op == "sep3":
        y = jax.lax.conv_general_dilated(
            x, p["dw"], (1, 1), "SAME", dimension_numbers=dn,
            feature_group_count=x.shape[-1])
        y = jax.lax.conv_general_dilated(y, p["pw"], (1, 1), "SAME",
                                         dimension_numbers=dn)
        return jax.nn.relu(y + p["b"])
    if op == "maxpool":
        return jax.lax.reduce_window(x, -jnp.inf, jax.lax.max,
                                     (1, 3, 3, 1), (1, 1, 1, 1), "SAME")
    if op == "avgpool":
        s = jax.lax.reduce_window(x, 0.0, jax.lax.add,
                                  (1, 3, 3, 1), (1, 1, 1, 1), "SAME")
        return s / 9.0
    return x  # identity


def _reduce(x):
    return jax.lax.reduce_window(x, -jnp.inf, jax.lax.max,
                                 (1, 2, 2, 1), (1, 2, 2, 1), "VALID")


# -- mode 1: fixed architecture (one trial) -----------------------------------

def init(rng: jax.Array, cfg: NasCnnConfig) -> Params:
    keys = jax.random.split(rng, len(cfg.ops) + 2)
    c = cfg.channels
    params: Params = {
        "stem": {"w": _he(keys[0], (3, 3, cfg.in_channels, c),
                          9 * cfg.in_channels),
                 "b": jnp.zeros((c,))},
        "layers": [_op_params(keys[i + 1], op, c)
                   for i, op in enumerate(cfg.ops)],
        "head": {"w": _he(keys[-1], (c, cfg.n_classes), c),
                 "b": jnp.zeros((cfg.n_classes,))},
    }
    return params


def apply(params: Params, images: jax.Array, cfg: NasCnnConfig) -> jax.Array:
    x = images.astype(cfg.dtype)
    x = _apply_op("conv3", params["stem"], x)
    for i, op in enumerate(cfg.ops):
        x = _apply_op(op, params["layers"][i], x)
        if (i + 1) % cfg.reduce_every == 0 and x.shape[1] > 2:
            x = _reduce(x)
    x = jnp.mean(x, axis=(1, 2))  # global average pool
    return x @ params["head"]["w"] + params["head"]["b"]


def loss_fn(params: Params, batch: dict[str, jax.Array], cfg: NasCnnConfig):
    logits = apply(params, batch["image"], cfg)
    labels = batch["label"]
    logp = jax.nn.log_softmax(logits)
    loss = -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))
    acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
    return loss, {"loss": loss, "accuracy": acc}


def _op_axes(op: str) -> Params:
    """Logical sharding axes per op — single source for both the fixed-arch
    model and the DARTS supernet."""
    if op in ("conv3", "conv5"):
        return {"w": (None, None, "conv_in", "conv_out"), "b": (None,)}
    if op == "sep3":
        return {"dw": (None, None, None, "conv_out"),
                "pw": (None, None, "conv_in", "conv_out"), "b": (None,)}
    return {}


def logical_axes(cfg: NasCnnConfig) -> Params:
    return {
        "stem": {"w": (None, None, "conv_in", "conv_out"), "b": (None,)},
        "layers": [_op_axes(op) for op in cfg.ops],
        "head": {"w": ("embed", None), "b": (None,)},
    }


# -- mode 2: DARTS supernet ---------------------------------------------------

def darts_init(rng: jax.Array, cfg: NasCnnConfig) -> Params:
    """Supernet: every layer holds params for ALL ops plus architecture
    logits alpha [n_layers, n_ops] (init 0 = uniform mixture)."""
    n_layers = len(cfg.ops)
    keys = jax.random.split(rng, n_layers * len(OP_NAMES) + 2)
    c = cfg.channels
    layers = []
    ki = 1
    for _ in range(n_layers):
        ops = {}
        for op in OP_NAMES:
            ops[op] = _op_params(keys[ki], op, c)
            ki += 1
        layers.append(ops)
    return {
        "stem": {"w": _he(keys[0], (3, 3, cfg.in_channels, c),
                          9 * cfg.in_channels), "b": jnp.zeros((c,))},
        "layers": layers,
        "alpha": jnp.zeros((n_layers, len(OP_NAMES)), jnp.float32),
        "head": {"w": _he(keys[-1], (c, cfg.n_classes), c),
                 "b": jnp.zeros((cfg.n_classes,))},
    }


def darts_apply(params: Params, images: jax.Array,
                cfg: NasCnnConfig) -> jax.Array:
    """All candidate ops run for every layer; the softmax(alpha) mixture is
    a dense weighted sum — branch-free, fully batched for XLA."""
    x = images.astype(cfg.dtype)
    x = _apply_op("conv3", params["stem"], x)
    weights = jax.nn.softmax(params["alpha"], axis=-1)
    for i, layer_ops in enumerate(params["layers"]):
        outs = jnp.stack([_apply_op(op, layer_ops[op], x)
                          for op in OP_NAMES])
        x = jnp.tensordot(weights[i], outs, axes=1)
        if (i + 1) % cfg.reduce_every == 0 and x.shape[1] > 2:
            x = _reduce(x)
    x = jnp.mean(x, axis=(1, 2))
    return x @ params["head"]["w"] + params["head"]["b"]


def darts_loss_fn(params: Params, batch: dict[str, jax.Array],
                  cfg: NasCnnConfig):
    logits = darts_apply(params, batch["image"], cfg)
    labels = batch["label"]
    logp = jax.nn.log_softmax(logits)
    loss = -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))
    acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
    return loss, {"loss": loss, "accuracy": acc}


def darts_logical_axes(cfg: NasCnnConfig) -> Params:
    fixed = logical_axes(cfg)
    layers = [{op: _op_axes(op) for op in OP_NAMES} for _ in cfg.ops]
    return {"stem": fixed["stem"], "layers": layers,
            "alpha": (None, None), "head": fixed["head"]}


def derive(alpha) -> tuple[str, ...]:
    """Read the discrete architecture off trained alphas (DARTS derive
    step): argmax op per layer."""
    idx = jnp.argmax(jnp.asarray(alpha), axis=-1)
    return tuple(OP_NAMES[int(i)] for i in idx)
