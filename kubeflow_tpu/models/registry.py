"""Model registry: name -> (Config, init, apply, loss_fn, logical_axes).

The analog of the reference's per-framework job kinds (TFJob/PyTorchJob pick a
user image); here a JAXJob spec names a registered model + config overrides.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable, NamedTuple


class ModelDef(NamedTuple):
    config_cls: type
    init: Callable
    apply: Callable
    loss_fn: Callable
    logical_axes: Callable


_REGISTRY: dict[str, ModelDef] = {}
_populated = False
_populate_lock = threading.Lock()


def register(name: str, model: ModelDef) -> None:
    _REGISTRY[name] = model


def get(name: str) -> ModelDef:
    if name not in _REGISTRY:
        _populate()
    if name not in _REGISTRY:
        raise KeyError(f"unknown model {name!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[name]


def names() -> list[str]:
    _populate()
    return sorted(_REGISTRY)


def make_config(name: str, overrides: dict[str, Any] | None = None):
    model = get(name)
    return model.config_cls(**(overrides or {}))


def config_with(cfg, **overrides):
    return dataclasses.replace(cfg, **overrides)


def _populate() -> None:
    """Thread-safe lazy registration: concurrent trial pods hit get() at
    once, and the flag must only flip AFTER every built-in is registered
    (flag-first left a window where a second thread saw an empty
    registry)."""
    global _populated
    if _populated:
        return
    with _populate_lock:
        if _populated:
            return
        _do_populate()
        _populated = True


def _do_populate() -> None:
    from kubeflow_tpu.models import (bert, llama, lora, mnist_cnn,
                                     moe_llama, nas_cnn, resnet, vit)

    register("llama", ModelDef(llama.LlamaConfig, llama.init, llama.apply,
                               llama.loss_fn, llama.logical_axes))
    register("llama_lora", ModelDef(lora.LoraLlamaConfig, lora.init,
                                    lora.apply, lora.loss_fn,
                                    lora.logical_axes))
    register("mixtral", ModelDef(moe_llama.MoELlamaConfig, moe_llama.init,
                                 moe_llama.apply, moe_llama.loss_fn,
                                 moe_llama.logical_axes))
    register("mnist_cnn", ModelDef(mnist_cnn.MnistConfig, mnist_cnn.init,
                                   mnist_cnn.apply, mnist_cnn.loss_fn,
                                   mnist_cnn.logical_axes))
    register("bert", ModelDef(bert.BertConfig, bert.init, bert.apply,
                              bert.loss_fn, bert.logical_axes))
    register("resnet", ModelDef(resnet.ResNetConfig, resnet.init, resnet.apply,
                                resnet.loss_fn, resnet.logical_axes))
    register("nas_cnn", ModelDef(nas_cnn.NasCnnConfig, nas_cnn.init,
                                 nas_cnn.apply, nas_cnn.loss_fn,
                                 nas_cnn.logical_axes))
    register("darts_supernet", ModelDef(
        nas_cnn.NasCnnConfig, nas_cnn.darts_init, nas_cnn.darts_apply,
        nas_cnn.darts_loss_fn, nas_cnn.darts_logical_axes))
    register("vit", ModelDef(vit.ViTConfig, vit.init, vit.apply,
                             vit.loss_fn, vit.logical_axes))
