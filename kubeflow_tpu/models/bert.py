"""BERT-style bidirectional encoder for fine-tune workloads.

BASELINE.json config #2 ("BERT-base fine-tune, 4-worker DDP -> 4-host JAXJob").
Same TPU-first structure as llama.py: functional params, scanned layers,
logical-axis sharding tree. Classification head for fine-tuning; masked-LM
head available via `apply(..., mlm=True)`.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from kubeflow_tpu.ops.attention import mha
from kubeflow_tpu.ops.norms import layer_norm

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    max_seq_len: int = 512
    n_classes: int = 2
    type_vocab: int = 2
    norm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @staticmethod
    def base() -> "BertConfig":
        return BertConfig()

    @staticmethod
    def tiny() -> "BertConfig":
        return BertConfig(vocab_size=512, d_model=64, n_layers=2, n_heads=8,
                          d_ff=128, max_seq_len=64)


def init(rng: jax.Array, cfg: BertConfig) -> Params:
    k = jax.random.split(rng, 10)
    pd = cfg.param_dtype
    d, f, L = cfg.d_model, cfg.d_ff, cfg.n_layers

    def dense(key, shape):
        # BERT convention: fixed-stddev truncated-normal-style init (0.02).
        return (jax.random.normal(key, shape, jnp.float32) * 0.02).astype(pd)

    return {
        "embed": dense(k[0], (cfg.vocab_size, d)),
        "pos_embed": dense(k[1], (cfg.max_seq_len, d)),
        "type_embed": dense(k[2], (cfg.type_vocab, d)),
        "embed_norm": {"w": jnp.ones((d,), pd), "b": jnp.zeros((d,), pd)},
        "layers": {
            "wqkv": dense(k[3], (L, d, 3 * d)),
            "bqkv": jnp.zeros((L, 3 * d), pd),
            "wo": dense(k[4], (L, d, d)),
            "bo": jnp.zeros((L, d), pd),
            "w1": dense(k[5], (L, d, f)),
            "b1": jnp.zeros((L, f), pd),
            "w2": dense(k[6], (L, f, d)),
            "b2": jnp.zeros((L, d), pd),
            "norm1": {"w": jnp.ones((L, d), pd), "b": jnp.zeros((L, d), pd)},
            "norm2": {"w": jnp.ones((L, d), pd), "b": jnp.zeros((L, d), pd)},
        },
        "pooler": {"w": dense(k[7], (d, d)), "b": jnp.zeros((d,), pd)},
        "classifier": {"w": dense(k[8], (d, cfg.n_classes)),
                       "b": jnp.zeros((cfg.n_classes,), pd)},
    }


def logical_axes(cfg: BertConfig) -> Params:
    return {
        "embed": ("vocab", "embed"),
        "pos_embed": (None, "embed"),
        "type_embed": (None, "embed"),
        "embed_norm": {"w": ("embed_no_fsdp",), "b": ("embed_no_fsdp",)},
        "layers": {
            "wqkv": ("layers", "embed", "qkv"),
            "bqkv": ("layers", "qkv"),
            "wo": ("layers", "qkv", "embed"),
            "bo": ("layers", "embed_no_fsdp"),
            "w1": ("layers", "embed", "mlp"),
            "b1": ("layers", "mlp"),
            "w2": ("layers", "mlp", "embed"),
            "b2": ("layers", "embed_no_fsdp"),
            "norm1": {"w": ("layers", "embed_no_fsdp"), "b": ("layers", "embed_no_fsdp")},
            "norm2": {"w": ("layers", "embed_no_fsdp"), "b": ("layers", "embed_no_fsdp")},
        },
        "pooler": {"w": ("embed", "mlp"), "b": (None,)},
        "classifier": {"w": ("embed", None), "b": (None,)},
    }


def _layer_body(cfg: BertConfig, x, layer, attn_mask):
    b, s, d = x.shape
    nh, hd = cfg.n_heads, cfg.head_dim
    qkv = x @ layer["wqkv"].astype(cfg.dtype) + layer["bqkv"].astype(cfg.dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(b, s, nh, hd)
    k = k.reshape(b, s, nh, hd)
    v = v.reshape(b, s, nh, hd)
    out = mha(q, k, v, causal=False, segment_ids=attn_mask)
    out = out.reshape(b, s, d) @ layer["wo"].astype(cfg.dtype) + layer["bo"].astype(cfg.dtype)
    x = layer_norm(x + out, layer["norm1"]["w"], layer["norm1"]["b"], cfg.norm_eps)
    h = jax.nn.gelu(x @ layer["w1"].astype(cfg.dtype) + layer["b1"].astype(cfg.dtype))
    h = h @ layer["w2"].astype(cfg.dtype) + layer["b2"].astype(cfg.dtype)
    x = layer_norm(x + h, layer["norm2"]["w"], layer["norm2"]["b"], cfg.norm_eps)
    return x, None


def apply(params: Params, tokens: jax.Array, cfg: BertConfig, *,
          attention_mask: jax.Array | None = None,
          token_type_ids: jax.Array | None = None) -> jax.Array:
    """tokens [B,S] -> pooled classification logits [B, n_classes]."""
    b, s = tokens.shape
    x = params["embed"].astype(cfg.dtype)[tokens]
    x = x + params["pos_embed"].astype(cfg.dtype)[None, :s]
    tt = token_type_ids if token_type_ids is not None else jnp.zeros_like(tokens)
    x = x + params["type_embed"].astype(cfg.dtype)[tt]
    x = layer_norm(x, params["embed_norm"]["w"], params["embed_norm"]["b"], cfg.norm_eps)

    # attention_mask [B,S] of 1/0 -> segment ids (0 = padding segment)
    seg = attention_mask if attention_mask is not None else jnp.ones((b, s), jnp.int32)
    body = partial(_layer_body, cfg, attn_mask=seg)
    if cfg.remat:
        body = jax.checkpoint(body)
    x, _ = jax.lax.scan(body, x, params["layers"])

    cls = x[:, 0]  # [CLS] token
    pooled = jnp.tanh(cls @ params["pooler"]["w"].astype(cfg.dtype)
                      + params["pooler"]["b"].astype(cfg.dtype))
    logits = pooled @ params["classifier"]["w"].astype(cfg.dtype) \
        + params["classifier"]["b"].astype(cfg.dtype)
    return logits.astype(jnp.float32)


def loss_fn(params: Params, batch: dict[str, jax.Array], cfg: BertConfig):
    logits = apply(params, batch["tokens"], cfg,
                   attention_mask=batch.get("attention_mask"))
    labels = batch["label"]
    logp = jax.nn.log_softmax(logits)
    loss = -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))
    acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
    return loss, {"loss": loss, "accuracy": acc}
