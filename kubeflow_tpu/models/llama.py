"""Llama-family transformer, TPU-first.

The flagship model for the BASELINE.json contract (Llama-3-8B JAXJob on
v5e-16 at >=40% MFU). The reference platform never implements a model — it
launches Megatron/DeepSpeed containers (SURVEY.md §2.2, L7); here the model is
part of the framework, designed around XLA/Pallas:

  - pure-functional param pytrees (no framework Module state) + logical-axis
    trees so any (data, fsdp, tensor, sequence) mesh layout is a rule change;
  - all L layers stacked on a leading axis and executed with ``lax.scan``
    (one compiled layer body — O(1) compile time in depth); shallow models
    can set ``scan_layers=False`` to unroll instead, trading O(L) compile
    for the removal of the scan's residual-stacking copies;
  - bf16 activations/weights with fp32 softmax/norm statistics;
  - GQA (n_kv_heads < n_heads), RoPE with explicit position offsets so
    sequence-parallel shards and KV-cache decode share one code path;
  - attention is pluggable: "xla" reference einsum, "flash" Pallas kernel,
    "ring" sequence-parallel ring attention.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from kubeflow_tpu.ops import quant
from kubeflow_tpu.ops.attention import mha, repeat_kv
from kubeflow_tpu.ops.norms import rms_norm
from kubeflow_tpu.ops.rope import apply_rope

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    attention_impl: str = "flash"  # flash | xla | ring | ulysses (flash
    # auto-selects the Pallas TPU kernel, blockwise-XLA off-TPU; ring/ulysses
    # are the sequence-parallel paths — shard_map islands over the ambient
    # mesh's `sequence` axis, §5.7)
    remat: bool = True
    # remat policy: "none" | "minimal" (checkpoint_dots) | "full"
    remat_policy: str = "minimal"
    # microbatches for the GPipe schedule when the mesh has a `stage` axis;
    # 0 = one microbatch per stage (minimum that fills the pipe)
    pipeline_microbatches: int = 0
    # True: layers run under lax.scan (compact HLO, fast compile — the
    # right call for deep models). False: python-loop unroll; for shallow
    # models this removes the scan's residual-stacking dynamic-update-slice
    # traffic (profiled at ~20% of the train step at L8/d2048: +3 MFU pts)
    scan_layers: bool = True
    # >0: sequence-chunked cross-entropy — lm_head + log-softmax run per
    # ce_chunk tokens under jax.checkpoint so the full [B, S, vocab] f32
    # logits never materialize (the seq-32k single-chip memory wall);
    # 0 = whole-sequence CE (faster at short seq, same numbers)
    ce_chunk: int = 0
    # serving DECODE/verify attention over the KV cache slab (ISSUE 15):
    # "xla" reference einsum, "flash" the fused Pallas flash-decode
    # kernel (ops/flash_decode.py — online softmax over KV blocks, int8
    # dequant fused at the block load, GQA regrouped in-kernel), "auto"
    # the selection policy (flash on TPU, xla elsewhere; KTPU_DECODE_ATTN
    # env overrides the default). Orthogonal to attention_impl, which
    # governs the TRAINING/prefill full-sequence attention.
    decode_attention_impl: str = "auto"
    # serving PREFILL chunk attention (ISSUE 20): "xla" the reference
    # mha einsum, "flash" the fused Pallas chunked-prefill kernel
    # (ops/flash_prefill.py — online softmax over KV blocks, q_offset
    # causal masking, int8 dequant fused at the block load), "auto" the
    # selection policy (flash on TPU, xla elsewhere; KTPU_PREFILL_ATTN
    # env overrides the default). Governs the serving prefill_inner/
    # prefill_continue_inner bodies — TRAINING attention stays on
    # attention_impl.
    prefill_attention_impl: str = "auto"

    def __post_init__(self):
        if self.attention_impl not in ("xla", "flash", "ring", "ulysses"):
            raise ValueError(f"unknown attention_impl {self.attention_impl!r}")
        if self.decode_attention_impl not in ("auto", "xla", "flash"):
            raise ValueError("unknown decode_attention_impl "
                             f"{self.decode_attention_impl!r}")
        if self.prefill_attention_impl not in ("auto", "xla", "flash"):
            raise ValueError("unknown prefill_attention_impl "
                             f"{self.prefill_attention_impl!r}")

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        return LlamaConfig(vocab_size=128256, d_model=4096, n_layers=32,
                           n_heads=32, n_kv_heads=8, d_ff=14336,
                           rope_theta=500000.0)

    @staticmethod
    def tiny(vocab_size: int = 512) -> "LlamaConfig":
        """Test-size config: real structure, toy dims (multiple-of-8 friendly)."""
        return LlamaConfig(vocab_size=vocab_size, d_model=64, n_layers=2,
                           n_heads=8, n_kv_heads=4, d_ff=128, max_seq_len=128,
                           rope_theta=10000.0)


def init(rng: jax.Array, cfg: LlamaConfig) -> Params:
    """Initialize stacked-layer params: every per-layer tensor has leading
    axis n_layers (the lax.scan carry axis)."""
    keys = jax.random.split(rng, 8)
    pd = cfg.param_dtype
    d, f, hd = cfg.d_model, cfg.d_ff, cfg.head_dim
    nh, nkv, L = cfg.n_heads, cfg.n_kv_heads, cfg.n_layers

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) / (fan_in**0.5)).astype(pd)

    return {
        "embed": dense(keys[0], (cfg.vocab_size, d), d),  # scaled like d for stability
        "layers": {
            "wq": dense(keys[1], (L, d, nh * hd), d),
            "wk": dense(keys[2], (L, d, nkv * hd), d),
            "wv": dense(keys[3], (L, d, nkv * hd), d),
            "wo": dense(keys[4], (L, nh * hd, d), nh * hd),
            "w_gate": dense(keys[5], (L, d, f), d),
            "w_up": dense(keys[6], (L, d, f), d),
            "w_down": dense(keys[7], (L, f, d), f),
            "attn_norm": jnp.ones((L, d), pd),
            "mlp_norm": jnp.ones((L, d), pd),
        },
        "final_norm": jnp.ones((d,), pd),
        # LM head is tied to embed by default (llama3 unties; keep explicit)
        "lm_head": dense(jax.random.fold_in(keys[0], 1), (d, cfg.vocab_size), d),
    }


def logical_axes(cfg: LlamaConfig) -> Params:
    """Logical sharding tree matching init()'s structure (see parallel.sharding)."""
    return {
        "embed": ("vocab", "embed"),
        "layers": {
            "wq": ("layers", "embed", "qkv"),
            "wk": ("layers", "embed", "qkv"),
            "wv": ("layers", "embed", "qkv"),
            "wo": ("layers", "qkv", "embed"),
            "w_gate": ("layers", "embed", "mlp"),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
            "attn_norm": ("layers", "embed_no_fsdp"),
            "mlp_norm": ("layers", "embed_no_fsdp"),
        },
        "final_norm": ("embed_no_fsdp",),
        "lm_head": ("embed", "vocab"),
    }


QUANT_LEAVES = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_params(params: Params) -> Params:
    """Weight-only int8 for SERVING (ops/quant.py): every matmul weight
    becomes {"q": int8, "s": f32 per-out-channel}; embed (a gather) and the
    norms (tiny) stay in param dtype. Decode re-reads all weights per step,
    so this halves the dominant HBM traffic vs bf16 (4x vs f32) while the
    MXU still computes in bf16. Training params are never quantized."""
    out = dict(params)
    out["layers"] = {
        k: (quant.quantize_int8(v) if k in QUANT_LEAVES else v)
        for k, v in params["layers"].items()}
    out["lm_head"] = quant.quantize_int8(params["lm_head"])
    return out


def logical_axes_for(params: Params, cfg: LlamaConfig) -> Params:
    """logical_axes matching `params`' ACTUAL structure: quantized leaves
    expand to {"q": <full axes>, "s": <axes minus the contracted dim>}."""
    base = logical_axes(cfg)

    def expand(axes, value):
        if quant.is_quantized(value):
            return {"q": axes, "s": axes[:-2] + (axes[-1],)}
        return axes

    return jax.tree.map(expand, base, params,
                        is_leaf=lambda x: isinstance(x, tuple))


def _attention(cfg: LlamaConfig, x, layer, positions, segment_ids):
    b, s, d = x.shape
    nh, nkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    q = quant.matmul(h, layer["wq"], cfg.dtype).reshape(b, s, nh, hd)
    k = quant.matmul(h, layer["wk"], cfg.dtype).reshape(b, s, nkv, hd)
    v = quant.matmul(h, layer["wv"], cfg.dtype).reshape(b, s, nkv, hd)
    q = apply_rope(q, positions, theta=cfg.rope_theta)
    k = apply_rope(k, positions, theta=cfg.rope_theta)

    if cfg.attention_impl == "flash":
        from kubeflow_tpu.ops.flash_attention import flash_attention

        out = flash_attention(q, k, v, causal=True, segment_ids=segment_ids)
    elif cfg.attention_impl in ("ring", "ulysses"):
        # sequence-parallel islands: the surrounding model runs under
        # GSPMD jit with seq-sharded activations; the attention op alone
        # drops to shard_map for its manual collectives (ppermute ring /
        # all-to-all reshard). Mesh comes from parallel.active_mesh —
        # degrade to plain attention when there's no seq axis to ride.
        # When `sequence` is ALREADY manual (a pipeline stage body that
        # manualized stage+sequence together), call the per-device bodies
        # directly — Shardy rejects the nested-island form.
        from kubeflow_tpu.parallel.mesh import (get_active_mesh,
                                                manual_axis_names,
                                                mesh_shape)

        mesh = get_active_mesh()
        seq_n = mesh_shape(mesh).get("sequence", 1) if mesh is not None else 1
        if seq_n == 1:
            out = mha(q, k, v, causal=True, segment_ids=segment_ids)
        elif "sequence" in manual_axis_names(mesh):
            if cfg.attention_impl == "ring":
                from kubeflow_tpu.ops.ring_attention import ring_attention

                out = ring_attention(q, k, v, causal=True,
                                     segment_ids=segment_ids)
            else:
                from kubeflow_tpu.ops.ulysses import ulysses_attention

                out = ulysses_attention(q, k, v, causal=True,
                                        segment_ids=segment_ids)
        elif cfg.attention_impl == "ring":
            from kubeflow_tpu.ops.ring_attention import ring_attention_sharded

            out = ring_attention_sharded(q, k, v, mesh, causal=True,
                                         segment_ids=segment_ids)
        else:
            from kubeflow_tpu.ops.ulysses import ulysses_attention_sharded

            out = ulysses_attention_sharded(q, k, v, mesh, causal=True,
                                            segment_ids=segment_ids)
    else:
        out = mha(q, k, v, causal=True, segment_ids=segment_ids)
    out = out.reshape(b, s, nh * hd)
    return x + quant.matmul(out, layer["wo"], cfg.dtype)


def _mlp(cfg: LlamaConfig, x, layer):
    # delegates to the serving MLP with no adapters — one SwiGLU body
    return _serving_mlp(cfg, x, layer)


def _layer_body(cfg: LlamaConfig, carry, layer, positions, segment_ids):
    x = carry
    x = _attention(cfg, x, layer, positions, segment_ids)
    x = _mlp(cfg, x, layer)
    return x, None


def apply_hidden(
    params: Params,
    tokens: jax.Array,
    cfg: LlamaConfig,
    *,
    positions: jax.Array | None = None,
    segment_ids: jax.Array | None = None,
) -> jax.Array:
    """Forward pass up to (and including) the final norm: [B, S] int
    tokens -> [B, S, d_model] activations, no lm_head projection. The
    chunked-CE loss path projects per sequence chunk so the [B, S, vocab]
    f32 logits never materialize whole (the 32k-context memory wall)."""
    b, s = tokens.shape
    if positions is None:
        positions = jnp.arange(s)
    x = params["embed"].astype(cfg.dtype)[tokens]  # [B,S,D] gather

    body = partial(_layer_body, cfg, positions=positions, segment_ids=segment_ids)
    if cfg.remat:
        policy = {
            "minimal": jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
            "full": jax.checkpoint_policies.nothing_saveable,
            "none": jax.checkpoint_policies.everything_saveable,
        }[cfg.remat_policy]
        body = jax.checkpoint(body, policy=policy)
    if cfg.scan_layers:
        x, _ = jax.lax.scan(body, x, params["layers"])
    else:
        for i in range(cfg.n_layers):
            layer = jax.tree.map(lambda p: p[i], params["layers"])
            x, _ = body(x, layer)

    return rms_norm(x, params["final_norm"], cfg.norm_eps)


def apply(
    params: Params,
    tokens: jax.Array,
    cfg: LlamaConfig,
    *,
    positions: jax.Array | None = None,
    segment_ids: jax.Array | None = None,
) -> jax.Array:
    """Forward pass: [B, S] int tokens -> [B, S, vocab] fp32 logits."""
    x = apply_hidden(params, tokens, cfg, positions=positions,
                     segment_ids=segment_ids)
    logits = quant.matmul_f32_out(x, params["lm_head"], cfg.dtype)
    return logits


def loss_fn(params: Params, batch: dict[str, jax.Array], cfg: LlamaConfig):
    """Next-token cross-entropy with optional loss mask. batch: tokens [B,S],
    optionally loss_mask [B,S] (1.0 where the target counts).

    On a mesh with a `stage` axis the whole forward+loss runs as a GPipe
    schedule instead (parallel.pipeline) — same math, pipelined execution."""
    from kubeflow_tpu.parallel.mesh import get_active_mesh, mesh_shape

    mesh = get_active_mesh()
    if mesh is not None and mesh_shape(mesh).get("stage", 1) > 1:
        from kubeflow_tpu.parallel.pipeline import pipelined_llama_loss

        return pipelined_llama_loss(params, batch, cfg, mesh,
                                    cfg.pipeline_microbatches or None)
    tokens = batch["tokens"]
    if cfg.ce_chunk:
        return _chunked_ce_loss(params, batch, cfg)
    # Forward on the FULL sequence, shift logits afterwards: S-1 wouldn't
    # divide a `sequence` mesh axis, and the slice lives in GSPMD-land where
    # resharding is legal (the shard_map attention islands only ever see S).
    logits = apply(params, tokens, cfg,
                   positions=jnp.arange(tokens.shape[1]),
                   segment_ids=batch.get("segment_ids"))[:, :-1]
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    token_loss = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    mask = batch.get("loss_mask")
    mask = jnp.ones_like(token_loss) if mask is None else mask[:, 1:]
    total = jnp.sum(token_loss * mask)
    denom = jnp.maximum(jnp.sum(mask), 1.0)
    return total / denom, {"loss": total / denom, "tokens": jnp.sum(mask)}


def _chunked_ce_loss(params: Params, batch: dict[str, jax.Array],
                     cfg: LlamaConfig):
    """Sequence-chunked cross-entropy (cfg.ce_chunk > 0): the lm_head
    projection + log-softmax run per ce_chunk-token slice under
    jax.checkpoint, so only ONE [B, C, vocab] f32 logits block is ever
    live (fwd AND bwd) instead of the whole [B, S, vocab] — at seq 32768
    x vocab 32000 the whole-sequence block is ~4 GiB x several copies,
    the single-chip long-context memory wall. Numerically the same loss
    as the plain path (parity-tested); requires S % ce_chunk == 0."""
    tokens = batch["tokens"]
    b, s = tokens.shape
    c = cfg.ce_chunk
    if s % c:
        raise ValueError(f"seq_len {s} must divide by ce_chunk {c}")
    h = apply_hidden(params, tokens, cfg,
                     positions=jnp.arange(s),
                     segment_ids=batch.get("segment_ids"))
    # targets roll left; the final position is masked off (no target)
    targets = jnp.concatenate([tokens[:, 1:], tokens[:, :1]], axis=1)
    valid = jnp.ones((b, s), jnp.float32).at[:, -1].set(0.0)
    mask = batch.get("loss_mask")
    if mask is not None:
        # plain path indexes loss_mask by TARGET position (mask[:, 1:])
        valid = valid * jnp.concatenate(
            [mask[:, 1:].astype(jnp.float32),
             jnp.zeros((b, 1), jnp.float32)], axis=1)
    n_chunks = s // c
    xs = (jnp.moveaxis(h.reshape(b, n_chunks, c, -1), 1, 0),
          jnp.moveaxis(targets.reshape(b, n_chunks, c), 1, 0),
          jnp.moveaxis(valid.reshape(b, n_chunks, c), 1, 0))

    @jax.checkpoint
    def chunk(carry, inp):
        hc, tc, vc = inp
        logits = quant.matmul_f32_out(hc, params["lm_head"], cfg.dtype)
        logp = jax.nn.log_softmax(logits, axis=-1)
        tl = -jnp.take_along_axis(logp, tc[..., None], axis=-1)[..., 0]
        total, denom = carry
        return (total + jnp.sum(tl * vc), denom + jnp.sum(vc)), None

    (total, denom), _ = jax.lax.scan(chunk, (jnp.float32(0.0),
                                             jnp.float32(0.0)), xs)
    denom = jnp.maximum(denom, 1.0)
    return total / denom, {"loss": total / denom, "tokens": denom}


# ---------------------------------------------------------------------------
# Serving path: KV-cache prefill + decode (the in-framework replacement for
# the reference's Triton/torchserve runtime containers, SURVEY.md §2.4/§2.6).
# Static shapes throughout: prompt lengths are bucketed by the serving
# scheduler; the cache is a fixed [L, slots, max_len, kv, hd] ring of slots.
# ---------------------------------------------------------------------------


def init_cache(cfg: LlamaConfig, n_slots: int, max_len: int,
               kv_quantize: str | None = None) -> Params:
    shape = (cfg.n_layers, n_slots, max_len, cfg.n_kv_heads, cfg.head_dim)
    if kv_quantize == "int8":
        sshape = shape[:-1]
        return {"k": jnp.zeros(shape, jnp.int8),
                "v": jnp.zeros(shape, jnp.int8),
                "k_s": jnp.zeros(sshape, jnp.float32),
                "v_s": jnp.zeros(sshape, jnp.float32)}
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


def quantize_kv(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-token-per-head symmetric int8 over head_dim: [..., hd] ->
    (int8 [..., hd], f32 scale [...]). Decode re-reads the whole cache every
    step, so int8 KV halves that HBM traffic vs bf16 (ops/quant.py's
    weight-only argument, applied to the cache)."""
    s = jnp.maximum(jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1),
                    1e-8) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / s[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, s


def dequantize_kv(q: jax.Array, s: jax.Array, dtype) -> jax.Array:
    return (q.astype(jnp.float32) * s[..., None]).astype(dtype)


def _adapted(h, layer, t: str, lora_layer, ids, dtype):
    """One serving matmul with an optional per-row LoRA path.

    h: [B, S, d_in]; lora_layer[t] = {"a": [A, d_in, r], "b": [A, r, d_out]}
    (adapter-stacked, THIS layer's slice; b is pre-scaled by alpha/rank);
    ids: [B] adapter index per row (0 = the zero adapter = base only).
    Multi-adapter batched serving: x@W once for the batch, plus the
    low-rank bypass gathered per row — S-LoRA's trick, XLA-shaped (the
    gather is tiny next to the W read decode is bound on)."""
    y = quant.matmul(h, layer[t], dtype)
    if lora_layer is None or t not in lora_layer:
        return y
    a = lora_layer[t]["a"][ids].astype(jnp.float32)  # [B, d_in, r]
    b = lora_layer[t]["b"][ids].astype(jnp.float32)  # [B, r, d_out]
    z = jnp.einsum("bsd,bdr->bsr", h.astype(jnp.float32), a)
    return y + jnp.einsum("bsr,bro->bso", z, b).astype(y.dtype)


def _project_qkv(cfg: LlamaConfig, layer, x, positions, lora_layer=None,
                 ids=None):
    b, s, _ = x.shape
    nh, nkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    q = _adapted(h, layer, "wq", lora_layer, ids, cfg.dtype).reshape(
        b, s, nh, hd)
    k = _adapted(h, layer, "wk", lora_layer, ids, cfg.dtype).reshape(
        b, s, nkv, hd)
    v = _adapted(h, layer, "wv", lora_layer, ids, cfg.dtype).reshape(
        b, s, nkv, hd)
    return (apply_rope(q, positions, theta=cfg.rope_theta),
            apply_rope(k, positions, theta=cfg.rope_theta), v)


def _serving_mlp(cfg: LlamaConfig, x, layer, lora_layer=None, ids=None):
    h = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
    gate = _adapted(h, layer, "w_gate", lora_layer, ids, cfg.dtype)
    up = _adapted(h, layer, "w_up", lora_layer, ids, cfg.dtype)
    return x + _adapted(jax.nn.silu(gate) * up, layer, "w_down",
                        lora_layer, ids, cfg.dtype)


def _wo(cfg: LlamaConfig, out, layer, lora_layer=None, ids=None):
    return _adapted(out, layer, "wo", lora_layer, ids, cfg.dtype)


def prefill_inner(layers: Params, x: jax.Array, positions: jax.Array,
                  cfg: LlamaConfig, lora: Params | None = None,
                  ids: jax.Array | None = None):
    """Layer-slab half of prefill: [B, S, D] activations through a
    contiguous slab of layers → (x, k, v [Ls, B, S, kv, hd]). `layers`
    may be ANY leading-axis slice of the full stack — prefill() runs the
    whole model through it, the pipeline stage runner
    (parallel/pipeline.py) feeds each stage its own slab. Keeping ONE
    body is what makes stage-sharded serving byte-exact against the
    single-program engine."""
    b, s = x.shape[:2]
    # resolved ONCE per trace (static): the whole compiled prefill menu
    # of an engine runs one prefill-attention impl — the mha einsum or
    # the fused Pallas chunked-prefill kernel (cfg.prefill_attention_impl)
    attn_impl = resolve_prefill_attn(cfg)

    def body(carry, inp):
        x = carry
        layer, ll = inp if lora is not None else (inp, None)
        q, k, v = _project_qkv(cfg, layer, x, positions, ll, ids)
        out = prefill_attention(cfg, q, k, v, q_offset=0, impl=attn_impl)
        x = x + _wo(cfg, out.reshape(b, s, -1), layer, ll, ids)
        x = _serving_mlp(cfg, x, layer, ll, ids)
        return x, (k, v)

    xs = (layers, lora) if lora is not None else layers
    return jax.lax.scan(body, x, xs)


def lm_head(params: Params, x: jax.Array, cfg: LlamaConfig) -> jax.Array:
    """final_norm + lm_head projection — the serving tail every prefill/
    decode wrapper (and the LAST pipeline stage) shares."""
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    return quant.matmul_f32_out(x, params["lm_head"], cfg.dtype)


def prefill(params: Params, tokens: jax.Array, cfg: LlamaConfig,
            lora: Params | None = None, ids: jax.Array | None = None):
    """Forward a (right-padded) prompt, returning logits and per-layer KV.

    tokens: [B, S] → (logits [B, S, vocab] fp32, k, v [L, B, S, kv, hd]).
    Pad positions produce garbage KV past the true length — callers track
    lengths and decode masks them out.

    `lora`/`ids`: optional multi-adapter batch (serving/llm.py
    `adapters=`): lora = {target: {"a": [L, A, d_in, r], "b": [L, A, r,
    d_out]}} (adapter-stacked per layer, b pre-scaled by alpha/rank),
    ids = [B] adapter index per row, 0 = base-only.
    """
    _, s = tokens.shape
    positions = jnp.arange(s)
    x = params["embed"].astype(cfg.dtype)[tokens]
    x, (ks, vs) = prefill_inner(params["layers"], x, positions, cfg,
                                lora, ids)
    return lm_head(params, x, cfg), ks, vs


def prefill_continue(params: Params, tail_tokens: jax.Array,
                     k_prefix: jax.Array, v_prefix: jax.Array,
                     cfg: LlamaConfig, lora: Params | None = None,
                     ids: jax.Array | None = None):
    """Continuation prefill: forward only the TAIL of a prompt whose prefix
    KV is already computed (prefix caching — serving/llm.py).

    tail_tokens: [B, T] (right-padded); k_prefix/v_prefix: [L, B, P, kv, hd]
    from a previous prefill of the shared prefix. Returns
    (logits [B, T, vocab] fp32, k_tail, v_tail [L, B, T, kv, hd]).
    The tail attends causally over prefix+tail (q_offset = P); pad tail
    positions produce garbage KV the caller masks by true lengths.
    """
    positions = k_prefix.shape[2] + jnp.arange(tail_tokens.shape[1])
    x = params["embed"].astype(cfg.dtype)[tail_tokens]
    x, (ks, vs) = prefill_continue_inner(params["layers"], x, k_prefix,
                                         v_prefix, positions, cfg,
                                         lora, ids)
    return lm_head(params, x, cfg), ks, vs


def prefill_continue_inner(layers: Params, x: jax.Array,
                           k_prefix: jax.Array, v_prefix: jax.Array,
                           positions: jax.Array, cfg: LlamaConfig,
                           lora: Params | None = None,
                           ids: jax.Array | None = None):
    """Layer-slab half of prefill_continue (see prefill_inner): `layers`
    and `k_prefix`/`v_prefix` may be any matching leading-axis slice of
    the stack — the pipeline stage runner hands each stage its own slab
    and prefix-KV slab."""
    b, t = x.shape[:2]
    p = k_prefix.shape[2]
    # static impl resolution, like prefill_inner: one impl per trace
    attn_impl = resolve_prefill_attn(cfg)

    def body(carry, inp):
        x = carry
        if lora is not None:
            layer, kp, vp, ll = inp
        else:
            (layer, kp, vp), ll = inp, None  # kp/vp: [B, P, kv, hd]
        q, k_new, v_new = _project_qkv(cfg, layer, x, positions, ll, ids)
        k_full = jnp.concatenate([kp.astype(cfg.dtype), k_new], axis=1)
        v_full = jnp.concatenate([vp.astype(cfg.dtype), v_new], axis=1)
        out = prefill_attention(cfg, q, k_full, v_full, q_offset=p,
                                impl=attn_impl)
        x = x + _wo(cfg, out.reshape(b, t, -1), layer, ll, ids)
        x = _serving_mlp(cfg, x, layer, ll, ids)
        return x, (k_new, v_new)

    xs = ((layers, k_prefix, v_prefix, lora)
          if lora is not None else (layers, k_prefix, v_prefix))
    return jax.lax.scan(body, x, xs)


def decode_step(params: Params, last_tokens: jax.Array, cache: Params,
                lengths: jax.Array, cfg: LlamaConfig,
                span: int | None = None, lora: Params | None = None,
                ids: jax.Array | None = None):
    """One continuous-batching decode step over all cache slots.

    last_tokens: [B] token per slot; lengths: [B] current KV lengths
    (position where this step's KV is written). Returns
    (logits [B, vocab] fp32, updated cache). Inactive slots just produce
    garbage logits the engine ignores — shapes stay static.

    `span` (static) bounds the attention to the cache's first `span` rows —
    the length-aware decode menu (serving/llm.py): when every active length
    is < span, attending over max_len would read/compute against rows the
    mask discards anyway. Decode is HBM-bound on those KV reads at long
    max_len, so the slice is the throughput lever. Caller guarantees
    lengths < span; writes still land in the full cache.

    This IS verify_step at S_v=1 — one attention body, so a masking or
    quantization change can never diverge the plain and speculative paths.
    """
    logits, new_cache = verify_step(params, last_tokens[:, None], cache,
                                    lengths, cfg, span=span, lora=lora,
                                    ids=ids)
    return logits[:, 0], new_cache


def verify_step(params: Params, tokens: jax.Array, cache: Params,
                lengths: jax.Array, cfg: LlamaConfig,
                span: int | None = None, lora: Params | None = None,
                ids: jax.Array | None = None):
    """Speculative-verify step: forward S_v tokens per slot in ONE pass.

    tokens: [B, S_v] — row b holds the slot's pending last token followed by
    S_v-1 draft tokens; they occupy positions lengths[b]..lengths[b]+S_v-1.
    Returns (logits [B, S_v, vocab] fp32, updated cache): logits[:, i] is the
    model's next-token distribution after consuming tokens[:, i] — the
    verifier accepts the longest draft prefix where argmax(logits[:, i]) ==
    tokens[:, i+1] (serving/llm.py). KV rows for ALL S_v positions are
    written (rejected rows become stale, masked by `lengths` and overwritten
    by later writes — same contract as decode_step's junk writes for
    inactive slots). With S_v=1 this is exactly decode_step.

    The per-slot position offsets are what distinguish this from a prefill:
    every slot verifies at a DIFFERENT depth in its cache, which is why the
    reference's GPU runtimes (⊘ vllm speculative worker) need a dedicated
    program here too. Decode is HBM-bound on weight+cache reads, so the
    extra S_v-1 query rows ride along nearly free — that asymmetry is the
    entire speculative-decoding bet.
    """
    x = params["embed"].astype(cfg.dtype)[tokens]  # [B, S_v, D]
    cache_keys = (("k", "v", "k_s", "v_s") if "k_s" in cache
                  else ("k", "v"))
    if "tbl" in cache:   # paged KV (ISSUE 19): the block tables ride along
        cache_keys = cache_keys + ("tbl",)
    cache_in = {name: cache[name] for name in cache_keys}
    x, new_cache = verify_inner(params["layers"], x, cache_in, lengths,
                                cfg, span=span, lora=lora, ids=ids)
    return lm_head(params, x, cfg), new_cache


def resolve_decode_attn(cfg: LlamaConfig) -> str:
    """The decode-attention impl this config resolves to ("xla"/"flash")
    under the ops/flash_decode selection policy — static, so each
    engine's compiled program menu covers exactly the selected impl."""
    from kubeflow_tpu.ops import flash_decode

    return flash_decode.resolve_impl(cfg.decode_attention_impl)


def resolve_prefill_attn(cfg: LlamaConfig) -> str:
    """The prefill-attention impl this config resolves to ("xla"/
    "flash") under the ops/flash_prefill selection policy — static per
    trace, the prefill twin of resolve_decode_attn."""
    from kubeflow_tpu.ops import flash_prefill

    return flash_prefill.resolve_impl(cfg.prefill_attention_impl)


def prefill_attention(cfg: LlamaConfig, q: jax.Array, k: jax.Array,
                      v: jax.Array, cks=None, cvs=None, *,
                      q_offset: int = 0, impl: str | None = None,
                      tables: jax.Array | None = None) -> jax.Array:
    """Causal GQA chunk attention for prefill — the prefill twin of
    decode_attention, THE pluggable seam of the TTFT hot path (ISSUE 20).

    q: [B, S_chunk, nh, hd] (post-RoPE, cfg.dtype) — row i sits at
    absolute position `q_offset + i` (a static python int: full prefill
    at 0, continuation chunks and radix prefix-cache-hit starts at the
    prefix length p — the engine groups continuation waves by (p, t));
    k/v: [B, T, kv, hd] prefix+chunk KV covering positions 0..T-1, in
    cfg.dtype or int8 with cks/cvs [B, T, kv] f32 per-token scales (the
    breakdown probe's cache-direct shape; the engine bodies pass float
    KV). Key position t is visible to row i iff t <= q_offset + i.
    Returns [B, S_chunk, nh, hd] in cfg.dtype (mha's shape contract, so
    the prefill bodies swap in without reshapes).

    impl: "xla" — the reference ops/attention.mha einsum; "flash" — the
    fused Pallas chunked-prefill kernel (ops/flash_prefill.py;
    interpret-mode off-TPU, so the differential tests run on CPU); None
    resolves cfg.prefill_attention_impl.

    PAGED mode: with `tables` [B, T//bt] int32, k/v are the POOL layer
    `[N_blocks, bt, kv, hd]` (cks/cvs `[N_blocks, bt, kv]`). The flash
    kernel indirects its kv-block grid axis through the scalar-
    prefetched table; the XLA path gathers the same blocks into the
    contiguous slab view and falls into the identical mha — the parity
    anchor that keeps slab and paged byte-comparable, exactly like
    decode_attention's twin paths.
    """
    if impl is None:
        impl = resolve_prefill_attn(cfg)
    b = q.shape[0]
    nkv, hd = cfg.n_kv_heads, cfg.head_dim
    if impl == "flash":
        from kubeflow_tpu.ops.flash_prefill import flash_prefill_attention

        return flash_prefill_attention(q, k, v, q_offset=q_offset,
                                       k_scale=cks, v_scale=cvs,
                                       scale=1.0 / (hd ** 0.5),
                                       tables=tables)
    if tables is not None:
        # XLA gather twin (see decode_attention): stage the table's
        # blocks as the contiguous [B, T, kv, hd] slab view, then the
        # SAME mha below runs unchanged.
        bt, nb = k.shape[1], tables.shape[1]
        k = jnp.take(k, tables, axis=0).reshape(b, nb * bt, nkv, hd)
        v = jnp.take(v, tables, axis=0).reshape(b, nb * bt, nkv, hd)
        if cks is not None:
            cks = jnp.take(cks, tables, axis=0).reshape(b, nb * bt, nkv)
            cvs = jnp.take(cvs, tables, axis=0).reshape(b, nb * bt, nkv)
    if cks is not None:
        # int8 cache probe path: dequantize the chunk's KV view in
        # cfg.dtype — prefill reads each key once (unlike decode's
        # re-reads), so the einsum reference keeps the simple form
        k = k.astype(cfg.dtype) * cks[..., None].astype(cfg.dtype)
        v = v.astype(cfg.dtype) * cvs[..., None].astype(cfg.dtype)
    return mha(q, k.astype(cfg.dtype), v.astype(cfg.dtype), causal=True,
               q_offset=q_offset)


def decode_attention(cfg: LlamaConfig, q: jax.Array, ck: jax.Array,
                     cv: jax.Array, cks, cvs, positions: jax.Array,
                     impl: str | None = None,
                     tables: jax.Array | None = None) -> jax.Array:
    """Grouped-query decode/verify attention over a span-sliced KV cache
    slab — THE pluggable seam of the serving hot loop (ISSUE 15).

    q: [B, S_v, nh, hd] (post-RoPE, cfg.dtype); ck/cv: [B, span, kv, hd]
    in cache dtype (int8 or cfg.dtype) with cks/cvs [B, span, kv] f32
    per-token scales when int8 (None otherwise); positions: [B, S_v]
    absolute key positions of the query rows — row i MUST sit at
    positions[:, 0] + i (the decode/verify contract; the flash kernel
    exploits it). Key t is visible to row i iff t <= positions[:, i].
    Returns [B, S_v, nh*hd] attention output in cfg.dtype.

    impl: "xla" — the reference einsum path (dequant fused into the
    einsum operands, f32 softmax); "flash" — the fused Pallas kernel
    (ops/flash_decode.py; interpret-mode off-TPU, so the differential
    tests run on CPU); None resolves cfg.decode_attention_impl.

    PAGED mode (ISSUE 19): with `tables` [B, span//bt] int32, ck/cv are
    the POOL layer `[N_blocks, bt, kv, hd]` (cks/cvs `[N_blocks, bt,
    kv]`) and row b's logical span is the concatenation of its table's
    blocks. The flash kernel indirects its kv-block grid axis through
    the scalar-prefetched table; the XLA path gathers the same blocks
    into the contiguous slab view and falls into the identical einsum —
    the parity anchor that makes slab vs paged byte-comparable.
    """
    if impl is None:
        impl = resolve_decode_attn(cfg)
    b, s_v = q.shape[:2]
    nh, nkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    if impl == "flash":
        from kubeflow_tpu.ops.flash_decode import flash_decode_attention

        out = flash_decode_attention(q, ck, cv, positions[:, 0],
                                     k_scale=cks, v_scale=cvs,
                                     scale=1.0 / (hd ** 0.5),
                                     tables=tables)
        return out.reshape(b, s_v, nh * hd)
    if tables is not None:
        # XLA gather twin: jnp.take stages the table's blocks as the
        # [B, span, kv, hd] slab view (the transient copy the
        # `kv_gather` breakdown bucket measures), then the SAME einsum
        # below runs unchanged — one masking/softmax body for slab and
        # paged, so the layouts can never diverge numerically.
        bt, nb = ck.shape[1], tables.shape[1]
        ck = jnp.take(ck, tables, axis=0).reshape(b, nb * bt, nkv, hd)
        cv = jnp.take(cv, tables, axis=0).reshape(b, nb * bt, nkv, hd)
        if cks is not None:
            cks = jnp.take(cks, tables, axis=0).reshape(b, nb * bt, nkv)
            cvs = jnp.take(cvs, tables, axis=0).reshape(b, nb * bt, nkv)
    # XLA reference: grouped-query attention WITHOUT repeat_kv — q
    # regroups to [B, kv, g, Sv, hd] and both einsums contract against
    # the [B, span, kv, hd] cache directly; materializing the 4x
    # head-expanded K/V (and, when quantized, a dequantized copy) would
    # add GiB-scale HBM traffic per step at 8B dims. The int8 cache
    # dequant stays INSIDE the einsum operand (convert + scale fuse into
    # the dot read); scales apply to the score/output instead of the
    # payload where the algebra allows.
    g = nh // nkv
    span = ck.shape[1]
    k_pos = jnp.arange(span)
    mask = (k_pos[None, None, None, :]
            <= positions[:, None, :, None])  # [B, 1, Sv, span]
    qg = jnp.moveaxis(q.reshape(b, s_v, nkv, g, hd), 1, 3)
    if cks is not None:
        att = jnp.einsum("bhgqd,bkhd->bhgqk", qg, ck.astype(cfg.dtype),
                         preferred_element_type=jnp.float32)
        att = att * jnp.moveaxis(cks, -1, 1)[:, :, None, None, :]
    else:
        att = jnp.einsum("bhgqd,bkhd->bhgqk", qg, ck,
                         preferred_element_type=jnp.float32)
    att = att * (1.0 / (hd ** 0.5))
    att = jnp.where(mask[:, :, None], att, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(att, axis=-1).astype(cfg.dtype)
    if cvs is not None:
        # v = vq * vs[..., None]: fold vs into probs' k axis so the
        # int8 payload feeds the dot un-materialized
        probs_s = probs * jnp.moveaxis(cvs, -1, 1)[
            :, :, None, None, :].astype(probs.dtype)
        out = jnp.einsum("bhgqk,bkhd->bqhgd", probs_s,
                         cv.astype(cfg.dtype))
    else:
        out = jnp.einsum("bhgqk,bkhd->bqhgd", probs, cv)
    return out.reshape(b, s_v, nh * hd)


def verify_inner(layers: Params, x: jax.Array, cache: Params,
                 lengths: jax.Array, cfg: LlamaConfig,
                 span: int | None = None, lora: Params | None = None,
                 ids: jax.Array | None = None, slot_start: int = 0):
    """Layer-slab half of verify_step: x [B, S_v, D] activations through
    a contiguous slab of layers against that slab's KV cache →
    (x, new_cache). The cache may hold MORE slots than x carries rows:
    `slot_start` names the first cache slot this batch occupies (the
    pipeline stage runner decodes one microbatch of slots at a time
    against the stage's full-slot cache slab; the single-program path
    always passes the full batch at slot_start 0, where the slicing is
    the identity). `lengths` is per-ROW of x (already sliced to the
    microbatch)."""
    b, s_v = x.shape[:2]
    paged = "tbl" in cache
    if paged:
        # paged KV (ISSUE 19): cache holds the POOL arrays [L, N_blocks,
        # bt, kv, hd] plus the per-slot block tables "tbl" [n_slots,
        # max_len // bt]. The tables are carried alongside (never
        # written per layer — pop them from the scan carry) and logical
        # coordinates indirect through them everywhere below.
        cache = dict(cache)
        tbl = cache.pop("tbl")
        bt = cache["k"].shape[2]
        max_len = tbl.shape[1] * bt
    else:
        max_len = cache["k"].shape[2]
    span = max_len if span is None else min(span, max_len)
    quantized = "k_s" in cache
    rows = slot_start + jnp.arange(b)
    positions = lengths[:, None] + jnp.arange(s_v)[None]  # [B, S_v]
    # drop mode: inactive slots can carry lengths near max_len — their junk
    # writes must vanish, not clamp onto the last live row
    idx = (rows[:, None], positions)
    full_batch = slot_start == 0 and cache["k"].shape[1] == b
    if paged:
        if span % bt:
            raise ValueError(
                f"paged span {span} must divide by block_tokens {bt}")
        # this batch's table rows, clipped to the attention span
        tbl_b = tbl[slot_start:slot_start + b, :span // bt]
        # write coordinates: position p of row r lands at block
        # tbl[r, p // bt], offset p % bt. Positions at/past max_len
        # (inactive slots' junk) — and any position whose table entry
        # was never allocated — indirect to block 0, the pool's trash
        # sentinel: the paged twin of the slab path's mode="drop".
        pos_c = jnp.minimum(positions, max_len - 1)
        blk = jnp.where(positions < max_len,
                        tbl[rows[:, None], pos_c // bt], 0)
        w_idx = (blk, positions % bt)
    else:
        w_idx = idx
    # resolved ONCE per trace (static): the whole compiled menu of an
    # engine runs one decode-attention impl — xla einsum or the fused
    # Pallas flash-decode kernel (cfg.decode_attention_impl)
    attn_impl = resolve_decode_attn(cfg)

    # The KV cache rides the scan as CARRY (not xs/ys): a per-layer
    # dynamic-update-slice on the carried buffer updates S_v rows in
    # place (XLA aliases while-loop carries), where stacked ys would
    # re-write the ENTIRE cache every decode step — at 8B dims that is
    # ~2 GiB of junk HBM write+read per step on the serving hot path.
    def body(carry, inp):
        x, cache_c = carry
        ll = None
        if lora is not None:
            layer, li, ll = inp
        else:
            layer, li = inp
        q, k_new, v_new = _project_qkv(cfg, layer, x, positions, ll, ids)
        if quantized:
            kq, ksc = quantize_kv(k_new)
            vq, vsc = quantize_kv(v_new)
            writes = {"k": kq, "v": vq, "k_s": ksc, "v_s": vsc}
        else:
            writes = {"k": k_new.astype(cache_c["k"].dtype),
                      "v": v_new.astype(cache_c["v"].dtype)}
        cache_c = {
            name: buf.at[(li,) + w_idx].set(writes[name], mode="drop")
            for name, buf in cache_c.items()}
        def layer_span(name):
            # index the layer FIRST, then slice the span: the other order
            # would stage an [L, B, span, ...] temp of the whole cache
            rows_all = jax.lax.dynamic_index_in_dim(
                cache_c[name], li, axis=0, keepdims=False)
            if paged:            # pool layer [N, bt, ...]: the TABLE does
                return rows_all  # the span slicing (tbl_b is span-clipped)
            if not full_batch:   # microbatch: this batch's slot window
                rows_all = jax.lax.slice_in_dim(
                    rows_all, slot_start, slot_start + b, axis=0)
            return jax.lax.slice_in_dim(rows_all, 0, span, axis=1)

        # attention over the slab rides the pluggable decode_attention
        # seam: the xla einsum reference or the fused Pallas flash-decode
        # kernel, per cfg.decode_attention_impl — ONE body for plain
        # decode (S_v=1) and speculative verify, so the impls can never
        # diverge the two paths
        out = decode_attention(
            cfg, q, layer_span("k"), layer_span("v"),
            layer_span("k_s") if quantized else None,
            layer_span("v_s") if quantized else None,
            positions, impl=attn_impl,
            tables=tbl_b if paged else None)
        x = x + _wo(cfg, out, layer, ll, ids)
        x = _serving_mlp(cfg, x, layer, ll, ids)
        return (x, cache_c), None

    n_layers = jax.tree.leaves(layers)[0].shape[0]
    layer_idx = jnp.arange(n_layers)
    xs = ((layers, layer_idx, lora) if lora is not None
          else (layers, layer_idx))
    (x, new_cache), _ = jax.lax.scan(body, (x, cache), xs)
    if paged:
        new_cache = dict(new_cache, tbl=tbl)   # tables pass through
    return x, new_cache


# ---------------------------------------------------------------------------
# HuggingFace checkpoint ingestion (SURVEY.md §2.4 huggingfaceserver slot;
# VERDICT r1 missing #2: real published weights must be servable).
# HF llama uses the same rotate_half RoPE convention as ops/rope.py, so the
# mapping is pure renaming + the torch Linear [out,in] -> x@W [in,out]
# transpose; per-layer tensors stack onto the leading lax.scan axis.
# ---------------------------------------------------------------------------

# our stacked-layer leaf -> (HF per-layer template, needs_transpose)
_HF_LAYER_MAP = {
    "wq": ("model.layers.{i}.self_attn.q_proj.weight", True),
    "wk": ("model.layers.{i}.self_attn.k_proj.weight", True),
    "wv": ("model.layers.{i}.self_attn.v_proj.weight", True),
    "wo": ("model.layers.{i}.self_attn.o_proj.weight", True),
    "w_gate": ("model.layers.{i}.mlp.gate_proj.weight", True),
    "w_up": ("model.layers.{i}.mlp.up_proj.weight", True),
    "w_down": ("model.layers.{i}.mlp.down_proj.weight", True),
    "attn_norm": ("model.layers.{i}.input_layernorm.weight", False),
    "mlp_norm": ("model.layers.{i}.post_attention_layernorm.weight", False),
}


def is_hf_checkpoint(path: str) -> bool:
    """True for a HuggingFace-format model dir (config.json + safetensors)."""
    import glob
    import os

    return (os.path.isdir(path)
            and os.path.exists(os.path.join(path, "config.json"))
            and bool(glob.glob(os.path.join(path, "*.safetensors"))))


def config_from_hf(path: str, **overrides: Any) -> LlamaConfig:
    """LlamaConfig from an HF config.json (llama-family field names)."""
    import json
    import os

    with open(os.path.join(path, "config.json")) as f:
        hf = json.load(f)
    heads = hf["num_attention_heads"]
    fields = dict(
        vocab_size=hf["vocab_size"],
        d_model=hf["hidden_size"],
        n_layers=hf["num_hidden_layers"],
        n_heads=heads,
        n_kv_heads=hf.get("num_key_value_heads", heads),
        d_ff=hf["intermediate_size"],
        max_seq_len=hf.get("max_position_embeddings", 8192),
        rope_theta=float(hf.get("rope_theta", 10000.0)),
        norm_eps=float(hf.get("rms_norm_eps", 1e-5)),
    )
    fields.update(overrides)
    return LlamaConfig(**fields)


def load_hf(path: str, cfg: LlamaConfig | None = None, *,
            mesh=None, rules=None) -> tuple[Params, LlamaConfig]:
    """Load an HF-format llama checkpoint dir into init()-shaped params.

    Returns (params, cfg). With `mesh`, every leaf is device_put with the
    sharding the logical-axis rules give it (parallel/sharding.py) — the
    same layout the trainer/serving engine use, so an 8B load lands
    directly sharded instead of materializing replicas per device.
    Handles sharded checkpoints (model.safetensors.index.json) and tied
    embeddings (no lm_head.weight -> embed.T). ⊘ kserve huggingfaceserver.
    """
    import json
    import os

    import numpy as np
    import torch
    from safetensors import safe_open

    if cfg is None:
        cfg = config_from_hf(path)

    index_path = os.path.join(path, "model.safetensors.index.json")
    if os.path.exists(index_path):
        with open(index_path) as f:
            weight_map = json.load(f)["weight_map"]
    else:
        import glob

        files = sorted(glob.glob(os.path.join(path, "*.safetensors")))
        if not files:
            raise FileNotFoundError(f"no *.safetensors under {path}")
        weight_map = {}
        for fn in files:
            with safe_open(fn, framework="pt") as f:
                for key in f.keys():
                    weight_map[key] = os.path.basename(fn)

    handles: dict[str, Any] = {}

    def tensor(name: str) -> np.ndarray:
        if name not in weight_map:
            raise KeyError(f"{name} missing from checkpoint {path}")
        fn = weight_map[name]
        if fn not in handles:
            handles[fn] = safe_open(os.path.join(path, fn), framework="pt")
        t = handles[fn].get_tensor(name)
        # torch tensors cover bf16 (numpy can't); fp32 round-trips exactly
        return t.to(torch.float32).numpy()

    try:
        pd = cfg.param_dtype
        embed = tensor("model.embed_tokens.weight").astype(pd)
        if "lm_head.weight" in weight_map:
            lm_head = tensor("lm_head.weight").T.astype(pd)
        else:  # tied embeddings (llama-2-style / tie_word_embeddings)
            lm_head = embed.T.copy()

        layers = {
            leaf: np.stack([
                (tensor(tpl.format(i=i)).T if transpose
                 else tensor(tpl.format(i=i))).astype(pd)
                for i in range(cfg.n_layers)])
            for leaf, (tpl, transpose) in _HF_LAYER_MAP.items()
        }
        params: Params = {
            "embed": embed,
            "layers": layers,
            "final_norm": tensor("model.norm.weight").astype(pd),
            "lm_head": lm_head,
        }
    finally:
        # release the mmapped shard files deterministically — a long-lived
        # serving process would otherwise hold every shard open forever
        for h in handles.values():
            close = getattr(h, "__exit__", None)
            if close is not None:
                close(None, None, None)
    expected = jax.eval_shape(lambda: init(jax.random.key(0), cfg))
    jax.tree.map(lambda got, want: None if got.shape == want.shape else
                 (_ for _ in ()).throw(ValueError(
                     f"shape mismatch: {got.shape} != {want.shape}")),
                 params, expected)

    if mesh is not None:
        from kubeflow_tpu.parallel.sharding import (shard_tree,
                                                    tree_logical_to_sharding)

        shardings = tree_logical_to_sharding(logical_axes(cfg), mesh, rules)
        params = shard_tree(params, shardings)
    else:
        params = jax.tree.map(jnp.asarray, params)
    return params, cfg


def flops_per_token(cfg: LlamaConfig, seq_len: int) -> float:
    """Training FLOPs/token (fwd+bwd ~ 6*N params + attention quadratic term)
    for MFU accounting. Matches the standard 6N + 12*L*H*S approximation
    (PaLM-appendix convention: the causal attention term is NOT halved,
    even though the Pallas kernel skips fully-masked KV blocks — at the
    bench shape attention is ~11% of the total, so the convention flatters
    causal MFU by a few percent of that share; kept because every public
    MFU number this is compared against uses the same convention)."""
    d, f, hd = cfg.d_model, cfg.d_ff, cfg.head_dim
    nh, nkv, L = cfg.n_heads, cfg.n_kv_heads, cfg.n_layers
    matmul_params = L * (d * nh * hd + 2 * d * nkv * hd + nh * hd * d + 3 * d * f)
    embed_params = cfg.vocab_size * d  # lm_head matmul counts; embed gather ~free
    attn_flops = 12 * L * nh * hd * seq_len  # 2 matmuls * 2 (fwd) * 3 (bwd) * S
    return 6.0 * (matmul_params + embed_params) + attn_flops
