"""MNIST CNN — BASELINE.json config #1 ("minimum slice").

The reference's analog is kubeflow/examples mnist TFJob user code (L7);
here it is a built-in model so the end-to-end JAXJob path has a seconds-scale
workload for tests and the smoke bench.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class MnistConfig:
    n_classes: int = 10
    c1: int = 32
    c2: int = 64
    hidden: int = 128
    dtype: Any = jnp.float32


def init(rng: jax.Array, cfg: MnistConfig) -> Params:
    k = jax.random.split(rng, 4)

    def he(key, shape, fan_in):
        return jax.random.normal(key, shape, jnp.float32) * (2.0 / fan_in) ** 0.5

    return {
        "conv1": {"w": he(k[0], (3, 3, 1, cfg.c1), 9), "b": jnp.zeros((cfg.c1,))},
        "conv2": {"w": he(k[1], (3, 3, cfg.c1, cfg.c2), 9 * cfg.c1),
                  "b": jnp.zeros((cfg.c2,))},
        "fc1": {"w": he(k[2], (7 * 7 * cfg.c2, cfg.hidden), 7 * 7 * cfg.c2),
                "b": jnp.zeros((cfg.hidden,))},
        "fc2": {"w": he(k[3], (cfg.hidden, cfg.n_classes), cfg.hidden),
                "b": jnp.zeros((cfg.n_classes,))},
    }


def logical_axes(cfg: MnistConfig) -> Params:
    return {
        "conv1": {"w": (None, None, "conv_in", "conv_out"), "b": (None,)},
        "conv2": {"w": (None, None, "conv_in", "conv_out"), "b": (None,)},
        "fc1": {"w": ("embed", "mlp"), "b": (None,)},
        "fc2": {"w": ("mlp", None), "b": (None,)},
    }


def _conv_block(x, p):
    x = jax.lax.conv_general_dilated(
        x, p["w"], window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    x = jax.nn.relu(x + p["b"])
    return jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID")


def apply(params: Params, images: jax.Array, cfg: MnistConfig) -> jax.Array:
    """images: [B, 28, 28, 1] -> logits [B, n_classes]."""
    x = images.astype(cfg.dtype)
    x = _conv_block(x, params["conv1"])
    x = _conv_block(x, params["conv2"])
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(x @ params["fc1"]["w"] + params["fc1"]["b"])
    return x @ params["fc2"]["w"] + params["fc2"]["b"]


def loss_fn(params: Params, batch: dict[str, jax.Array], cfg: MnistConfig):
    logits = apply(params, batch["image"], cfg)
    labels = batch["label"]
    logp = jax.nn.log_softmax(logits)
    loss = -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))
    acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
    return loss, {"loss": loss, "accuracy": acc}
