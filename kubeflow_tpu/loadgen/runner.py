"""Scenario runner: replay a trace against a REAL engine.

Open-loop replay through the ordinary `LLMEngine.submit` path — the same
code live HTTP traffic takes — honoring scheduled arrival instants,
tenant/adapter routing, and client cancellations. The runner is the only
loadgen piece that touches wall clocks; everything it produces reduces
through `loadgen.slo` (pure math) into the committed summary.

Conventions (shared with bench._poisson_run): arrivals coming due while a
blocking engine.step() runs are submitted late but keep their SCHEDULED
arrival as the TTFT epoch — dropping that wait would bias the percentiles
low. Cancellation fires `cancel_after_s` after the scheduled arrival; a
request that finished first simply keeps its result (the client got the
answer before leaving), so `client_cancelled` marks only requests the
cancel actually cut.
"""

from __future__ import annotations

import time
from typing import Any

from kubeflow_tpu.loadgen.slo import RequestRecord, summarize
from kubeflow_tpu.loadgen.trace import Trace, generate_trace, trace_sha256


def run_trace(engine, trace: Trace, *, controller=None,
              max_wall_s: float | None = None) -> dict[str, Any]:
    """Replay `trace` to completion; returns {"records", "summary",
    "wall_s", "timed_out"}. `controller` (loadgen.control.SLOController)
    gets completed-request TTFTs and a control tick each loop."""
    from kubeflow_tpu.serving.scheduler import QueueFull, PromptTooLong

    cfg = trace.config
    if max_wall_s is None:
        # generous: the trace window plus time to drain a saturated queue
        max_wall_s = cfg.duration_s * 4.0 + 60.0
    # fail BEFORE replay, not mid-loop: every adapter the trace routes to
    # must be loaded in this engine
    need = {r.adapter for r in trace.requests if r.adapter is not None}
    have = set(getattr(engine, "_adapter_idx", {}) or {})
    if need - have:
        raise ValueError(
            f"trace routes to adapters {sorted(need - have)} the engine "
            f"does not serve (loaded: {sorted(have)})")
    reqs = trace.requests
    records: dict[int, RequestRecord] = {}
    rid_of: dict[int, int] = {}         # trace index -> engine rid
    cancels: list[tuple[float, int]] = []   # (due_rel_s, trace index)
    cancelled_by_client: set[int] = set()
    next_arrival = 0
    t0 = time.monotonic()
    timed_out = False

    def now_rel() -> float:
        return time.monotonic() - t0

    def finalize(idx: int) -> None:
        """Read timing BEFORE release, normalize to run-relative times."""
        r = reqs[idx]
        rid = rid_of.pop(idx)
        tm = engine.request_timing(rid)
        records[idx] = RequestRecord(
            index=idx, tenant=r.tenant, arrival_s=r.arrival_s,
            max_new_tokens=r.max_new_tokens, adapter=r.adapter,
            submit_s=(tm["submit_s"] - t0
                      if tm["submit_s"] is not None else None),
            first_token_s=(tm["first_token_s"] - t0
                           if tm["first_token_s"] is not None else None),
            finish_s=(tm["finish_s"] - t0
                      if tm["finish_s"] is not None else None),
            n_tokens=tm["n_tokens"],
            finish_reason=engine.finish_reason(rid),
            client_cancelled=idx in cancelled_by_client)
        if controller is not None:
            ttft = records[idx].ttft_ms()
            if ttft is not None:
                controller.observe(ttft)
        engine.release(rid)

    while len(records) < len(reqs):
        now = now_rel()
        if now > max_wall_s:
            timed_out = True
            break
        # submit due arrivals (scheduled epoch kept by the record)
        while next_arrival < len(reqs) \
                and reqs[next_arrival].arrival_s <= now:
            r = reqs[next_arrival]
            try:
                rid = engine.submit(list(r.prompt), r.max_new_tokens,
                                    adapter=r.adapter, tenant=r.tenant)
                rid_of[r.index] = rid
                if r.cancel_after_s is not None:
                    cancels.append((r.arrival_s + r.cancel_after_s,
                                    r.index))
            except (QueueFull, PromptTooLong):
                # admission control / overload: an immediate, recorded
                # rejection (finish_reason "rejected")
                records[r.index] = RequestRecord(
                    index=r.index, tenant=r.tenant,
                    arrival_s=r.arrival_s,
                    max_new_tokens=r.max_new_tokens, adapter=r.adapter)
            next_arrival += 1
        # client disconnects that came due
        if cancels:
            due = [i for t, i in cancels if t <= now]
            cancels = [(t, i) for t, i in cancels if t > now]
            for idx in due:
                rid = rid_of.get(idx)
                if rid is not None and not engine.is_done(rid):
                    if engine.cancel(rid):
                        cancelled_by_client.add(idx)
        worked = engine.step()
        # collect everything that finished
        for idx in [i for i, rid in rid_of.items()
                    if engine.is_done(rid)]:
            finalize(idx)
        if controller is not None:
            controller.maybe_adjust(engine, now_rel())
        if not worked:
            # idle: sleep to the next scheduled event instead of spinning
            horizon = [t0 + max_wall_s]
            if next_arrival < len(reqs):
                horizon.append(t0 + reqs[next_arrival].arrival_s)
            if cancels:
                horizon.append(t0 + min(t for t, _ in cancels))
            if rid_of:
                horizon.append(time.monotonic() + 0.001)
            time.sleep(max(0.0, min(horizon) - time.monotonic()))
    if timed_out:
        # cancel everything outstanding, drain once, record honestly
        for idx, rid in list(rid_of.items()):
            engine.cancel(rid)
        engine.run_until_idle()
        for idx in list(rid_of):
            finalize(idx)
        for r in reqs:
            # arrivals the wall ran out before: "unsubmitted", NOT
            # "rejected" — the engine never saw them, and the committed
            # rejected column must mean admission control fired
            records.setdefault(r.index, RequestRecord(
                index=r.index, tenant=r.tenant, arrival_s=r.arrival_s,
                max_new_tokens=r.max_new_tokens, adapter=r.adapter,
                finish_reason="unsubmitted"))
    wall = now_rel()
    recs = [records[i] for i in sorted(records)]
    out = {
        "records": recs,
        "summary": summarize(recs, ttft_slo_ms=cfg.ttft_slo_ms,
                             tpot_slo_ms=cfg.tpot_slo_ms,
                             duration_s=max(wall, 1e-9)),
        "wall_s": round(wall, 3),
        "timed_out": timed_out,
    }
    return out


def run_scenario(engine, scenario, *, max_wall_s: float | None = None,
                 fault_script: str | None = None) -> dict[str, Any]:
    """Generate a scenario's trace, apply its fairness/control knobs, and
    replay it. Returns the committed-record shape the bench section and
    the floor gate consume: config echo + trace hash + aggregate +
    per-tenant SLO table (+ the SLO controller's chunk trajectory).

    A fault script (the scenario's `fault_script`, or the override
    argument) turns the replay into a chaos run: the script is
    materialized onto the trace's window, armed on the engine's
    supervisor (the engine must be an `EngineSupervisor` — a bare engine
    has no recovery story to inject faults into), and the supervisor's
    zero-lost accounting + fired-event log ride the committed record
    under `chaos`."""
    from kubeflow_tpu.loadgen.control import SLOController

    trace = generate_trace(scenario.trace)
    script_name = fault_script or scenario.fault_script
    script = None
    if script_name:
        from kubeflow_tpu.chaos import load_fault_script, script_sha256

        if not hasattr(engine, "arm_faults"):
            raise ValueError(
                f"scenario carries fault script {script_name!r} but the "
                "engine is not supervised — wrap it in "
                "serving.agent.EngineSupervisor")
        script = load_fault_script(script_name,
                                   duration_s=scenario.trace.duration_s)
        engine.arm_faults(script)
    engine.set_tenant_limits(scenario.tenant_max_active,
                             scenario.tenant_max_queued)
    controller = None
    if scenario.slo_chase:
        controller = SLOController(scenario.ttft_target_ms,
                                   interval_s=scenario.control_interval_s)
    try:
        res = run_trace(engine, trace, controller=controller,
                        max_wall_s=max_wall_s)
    finally:
        engine.set_tenant_limits(0, 0)   # never leak caps to the next run
    out = {
        "scenario": scenario.name,
        "trace_sha256": trace_sha256(trace),
        "n_requests": len(trace.requests),
        "seed": scenario.trace.seed,
        "wall_s": res["wall_s"],
        "timed_out": res["timed_out"],
        **res["summary"],
    }
    if script is not None:
        out["chaos"] = {
            "fault_script": script_name,
            "script_sha256": script_sha256(script),
            "events_scheduled": [e.to_json() for e in script.events],
            "events_fired": engine.injector.log(),
            "accounting": engine.accounting(),
        }
    if controller is not None:
        out["slo_chase"] = {
            "ttft_target_ms": scenario.ttft_target_ms,
            "final_chunk": engine.decode_chunk,
            "trajectory": controller.trajectory,
        }
    return out
