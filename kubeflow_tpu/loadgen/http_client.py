"""Real-socket HTTP/SSE load client — the dataplane-honest half of the
loadgen story (ISSUE 12).

`run_trace`/`run_scenario` replay through the in-process engine submit
path; everything they measure therefore EXCLUDES the HTTP layer — the
ModelServer, the SSE framing, the router's failover, the keepalive
machinery that holds a stream open across an engine restart. This module
replays through an actual TCP socket against a running ModelServer (or a
Router in front of a fleet), speaking the OpenAI SSE protocol, so chaos
claims ("a streaming client survives a mid-stream engine crash") are
measured where the client lives, not where the engine does.

`stream_completion` drives ONE SSE completion and returns everything a
verifier needs: the token ids actually delivered (byte-parity evidence),
keepalive comments observed (the restart-window liveness signal), typed
error events (`mid_stream_failure` carries `tokens_delivered` — the
resume point), duplicate-[DONE]/usage counting, and wall-clock marks.
`run_trace_http` replays a whole loadgen trace open-loop over sockets
and reduces to the same `loadgen.slo` summary as the in-process runner,
so HTTP-path and engine-path records are directly comparable.
"""

from __future__ import annotations

import http.client
import json
import socket
import threading
import time
from typing import Any

from kubeflow_tpu.loadgen.slo import RequestRecord, summarize
from kubeflow_tpu.loadgen.trace import Trace


def stream_completion(port: int, payload: dict[str, Any], *,
                      host: str = "127.0.0.1",
                      path: str = "/openai/v1/completions",
                      headers: dict[str, str] | None = None,
                      timeout_s: float = 60.0,
                      cancel_after_s: float | None = None
                      ) -> dict[str, Any]:
    """Drive one streaming completion over a raw socket.

    Returns a dict:
      status          HTTP status (200 = the stream committed)
      body            decoded JSON body for non-200 answers (else None)
      token_ids       every token chunk's token_id, in delivery order
      text            concatenated text deltas
      finish_reason   from the final chunk (None if the stream died)
      usage           the final chunk's usage object (None if absent)
      usage_count     how many chunks carried a usage object (MUST be 1
                      on a healthy stream — the no-duplicate contract)
      done_count      how many `data: [DONE]` lines arrived (MUST be 1)
      keepalives      SSE comment lines observed (restart-window sign)
      errors          data events carrying an "error" member (typed
                      mid-stream failures, permanent-fail terminals)
      client_cancelled True when cancel_after_s closed the socket first
      t_request_s / t_first_token_s / t_done_s   absolute monotonic marks
    """
    out: dict[str, Any] = {
        "status": None, "body": None, "token_ids": [], "text": "",
        "finish_reason": None, "usage": None, "usage_count": 0,
        "done_count": 0, "keepalives": 0, "errors": [],
        "client_cancelled": False,
        "t_request_s": time.monotonic(), "t_first_token_s": None,
        "t_done_s": None,
    }
    body = dict(payload)
    body.setdefault("stream", True)
    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    resp = None
    try:
        hdrs = {"Content-Type": "application/json"}
        if headers:
            hdrs.update(headers)
        conn.request("POST", path, body=json.dumps(body).encode(),
                     headers=hdrs)
        resp = conn.getresponse()
        out["status"] = resp.status
        ctype = resp.getheader("Content-Type") or ""
        if not ctype.startswith("text/event-stream"):
            raw = resp.read()
            try:
                out["body"] = json.loads(raw) if raw else None
            except ValueError:
                out["body"] = {"raw": raw.decode("utf-8", "replace")}
            return out
        deadline = time.monotonic() + timeout_s
        cancel_at = (out["t_request_s"] + cancel_after_s
                     if cancel_after_s is not None else None)
        # with Connection: close responses http.client detaches the
        # socket INTO the response (conn.sock goes None at
        # getresponse()), so the wake-up timeouts must be set on the
        # response's underlying socket, not the connection's
        sock = conn.sock
        if sock is None:
            raw = getattr(getattr(resp, "fp", None), "raw", None)
            sock = getattr(raw, "_sock", None)
        while True:
            now = time.monotonic()
            if cancel_at is not None and now >= cancel_at:
                out["client_cancelled"] = True
                return out   # finally closes the socket — the client left
            if now >= deadline:
                return out
            if sock is not None:
                # readline must wake for the cancel instant, not sit out
                # the full timeout on a quiet stream
                wake = deadline
                if cancel_at is not None:
                    wake = min(wake, cancel_at)
                sock.settimeout(max(0.02, wake - now))
            try:
                line = resp.readline()
            except (socket.timeout, TimeoutError):
                continue
            if not line:
                return out   # server EOF
            if line.startswith(b":"):
                out["keepalives"] += 1
                continue
            if not line.startswith(b"data: "):
                continue
            data = line[len(b"data: "):].strip()
            if data == b"[DONE]":
                out["done_count"] += 1
                out["t_done_s"] = time.monotonic()
                continue   # keep reading: a duplicate [DONE] must COUNT
            try:
                chunk = json.loads(data)
            except ValueError:
                continue
            if "error" in chunk:
                out["errors"].append(chunk["error"])
                continue
            if chunk.get("usage") is not None:
                out["usage"] = chunk["usage"]
                out["usage_count"] += 1
            for ch in chunk.get("choices", ()):
                if ch.get("token_id") is not None:
                    if out["t_first_token_s"] is None:
                        out["t_first_token_s"] = time.monotonic()
                    out["token_ids"].append(int(ch["token_id"]))
                delta = (ch.get("text") if "text" in ch
                         else (ch.get("delta") or {}).get("content"))
                if delta:
                    out["text"] += delta
                if ch.get("finish_reason"):
                    out["finish_reason"] = ch["finish_reason"]
    except OSError as e:
        out["errors"].append({"type": "transport", "message": str(e)})
        return out
    finally:
        # with Connection: close responses, http.client detaches the
        # socket into the response object — closing the RESPONSE is what
        # actually sends FIN (a cancel must look like a vanished client)
        try:
            if resp is not None:
                resp.close()
        except OSError:
            pass
        try:
            conn.close()
        except OSError:
            pass


def run_trace_http(port: int, trace: Trace, *, model: str = "llm",
                   host: str = "127.0.0.1",
                   max_wall_s: float | None = None,
                   max_concurrency: int = 32,
                   timeout_s: float = 60.0) -> dict[str, Any]:
    """Replay a loadgen trace open-loop through a REAL socket: one SSE
    request per trace arrival (scheduled instants honored, like
    `run_trace`), tenants carried via the OpenAI `user` field (which is
    also the router's affinity key), client cancellations as actual
    socket closes. Reduces to the standard `loadgen.slo` summary so the
    HTTP-path record reads exactly like the engine-path one; the raw
    per-stream results ride along under "streams" for byte-parity and
    keepalive assertions."""
    cfg = trace.config
    if max_wall_s is None:
        max_wall_s = cfg.duration_s * 4.0 + 60.0
    gate = threading.Semaphore(max_concurrency)
    results: dict[int, dict[str, Any]] = {}
    lock = threading.Lock()
    t0 = time.monotonic()

    def worker(r) -> None:
        with gate:
            res = stream_completion(
                port, {
                    "model": model, "prompt": list(r.prompt),
                    "max_tokens": r.max_new_tokens, "temperature": 0.0,
                    **({"user": r.tenant} if r.tenant else {}),
                },
                host=host, timeout_s=timeout_s,
                cancel_after_s=r.cancel_after_s)
        with lock:
            results[r.index] = res

    threads: list[threading.Thread] = []
    unsubmitted: list[Any] = []
    for r in trace.requests:
        wait = r.arrival_s - (time.monotonic() - t0)
        if wait > 0:
            time.sleep(wait)
        if time.monotonic() - t0 > max_wall_s:
            unsubmitted.append(r)
            continue
        t = threading.Thread(target=worker, args=(r,), daemon=True,
                             name=f"http-load-{r.index}")
        t.start()
        threads.append(t)
    join_deadline = t0 + max_wall_s + timeout_s
    for t in threads:
        t.join(max(0.0, join_deadline - time.monotonic()))
    timed_out = bool(unsubmitted) or any(t.is_alive() for t in threads)

    records: list[RequestRecord] = []
    for r in trace.requests:
        res = results.get(r.index)
        if res is None:
            records.append(RequestRecord(
                index=r.index, tenant=r.tenant, arrival_s=r.arrival_s,
                max_new_tokens=r.max_new_tokens, adapter=r.adapter,
                finish_reason="unsubmitted"))
            continue
        if res["status"] != 200:
            records.append(RequestRecord(
                index=r.index, tenant=r.tenant, arrival_s=r.arrival_s,
                max_new_tokens=r.max_new_tokens, adapter=r.adapter,
                submit_s=res["t_request_s"] - t0,
                finish_reason="rejected"))
            continue
        if res["client_cancelled"]:
            reason = "cancelled"
        elif res["errors"] or not res["done_count"]:
            reason = "error"
        else:
            reason = res["finish_reason"] or "length"
        records.append(RequestRecord(
            index=r.index, tenant=r.tenant, arrival_s=r.arrival_s,
            max_new_tokens=r.max_new_tokens, adapter=r.adapter,
            submit_s=res["t_request_s"] - t0,
            first_token_s=(res["t_first_token_s"] - t0
                           if res["t_first_token_s"] is not None else None),
            finish_s=(res["t_done_s"] - t0
                      if res["t_done_s"] is not None else None),
            n_tokens=len(res["token_ids"]),
            finish_reason=reason,
            client_cancelled=res["client_cancelled"]))
    wall = time.monotonic() - t0
    return {
        "records": records,
        "streams": results,
        "summary": summarize(records, ttft_slo_ms=cfg.ttft_slo_ms,
                             tpot_slo_ms=cfg.tpot_slo_ms,
                             duration_s=max(wall, 1e-9)),
        "wall_s": round(wall, 3),
        "timed_out": timed_out,
    }
