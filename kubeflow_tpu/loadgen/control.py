"""SLO-aware serving control: turn a `ttft_target_ms` knob into engine
settings.

decode_chunk is the measured latency/throughput dial (docs/ARCHITECTURE,
8d25015): a prefill wave must drain the in-flight decode chunk first, so
TTFT carries ~one chunk of decode wall time — at 8B/32 slots chunk 8
served 1055 tok/s at TTFT p50 ~465 ms while chunk 4 gave up 6% throughput
for p50 ~217 ms. Two surfaces here:

- `pick_decode_chunk`: the STATIC pick — largest chunk whose measured
  TTFT sits under the target, from a committed (chunk -> ttft_ms) table
  (defaults to the 8B measurements). Use at engine/scenario setup.
- `SLOController`: the LIVE loop — an observed-TTFT EMA against the
  target re-picks the chunk at a fixed control interval through
  `engine.set_decode_chunk` (clamped to the warmed menu, applied at the
  next chunk boundary — live traffic never waits on XLA). Multiplicative
  decrease on misses, cautious increase when comfortably under target.

Admission control composes via `engine.set_tenant_limits` (the scheduler
owns per-tenant share caps); the slo-chase scenario drives both.
"""

from __future__ import annotations

from typing import Any, Mapping

#: measured TTFT p50 per decode_chunk at the 8B/32-slot operating point
#: (8d25015); the slope — not the absolute values — is what transfers to
#: other models, so the controller treats this as a starting ranking and
#: the live EMA as truth.
MEASURED_CHUNK_TTFT_MS: dict[int, float] = {4: 217.0, 8: 465.0}


def pick_decode_chunk(ttft_target_ms: float,
                      table: Mapping[int, float] | None = None,
                      max_chunk: int = 8) -> int:
    """Largest chunk (<= max_chunk) whose measured TTFT meets the target;
    the smallest tabled chunk when none does (latency-floor fallback)."""
    table = dict(table or MEASURED_CHUNK_TTFT_MS)
    fits = [c for c, ttft in table.items()
            if c <= max_chunk and ttft <= ttft_target_ms]
    if fits:
        return max(fits)
    return min(c for c in table if c <= max_chunk) if any(
        c <= max_chunk for c in table) else 1


class SLOController:
    """Feedback re-pick of decode_chunk from a live TTFT EMA.

    observe() feeds completed-request TTFTs; maybe_adjust() applies the
    policy at most once per control interval:
      - EMA > target          -> halve the chunk (shed queueing latency)
      - EMA < recover*target  -> double it (recover throughput headroom)
    The engine clamps to its warmed menu, so the controller can never
    push live traffic onto the XLA compiler. The trajectory list is the
    committed evidence that the knob actually moved under load."""

    def __init__(self, ttft_target_ms: float, *,
                 interval_s: float = 5.0, alpha: float = 0.3,
                 recover_frac: float = 0.4):
        if ttft_target_ms <= 0:
            raise ValueError("ttft_target_ms must be positive")
        self.target_ms = float(ttft_target_ms)
        self.interval_s = float(interval_s)
        self.alpha = float(alpha)
        self.recover_frac = float(recover_frac)
        self.ema_ms: float | None = None
        self._last_adjust_s: float | None = None
        self.trajectory: list[dict[str, Any]] = []

    def observe(self, ttft_ms: float) -> None:
        if self.ema_ms is None:
            self.ema_ms = float(ttft_ms)
        else:
            self.ema_ms += self.alpha * (float(ttft_ms) - self.ema_ms)

    def maybe_adjust(self, engine, now_s: float) -> int | None:
        """One control tick; returns the newly applied chunk (None = no
        change). `now_s` is the runner's clock so replays stay testable."""
        if self._last_adjust_s is None:
            self._last_adjust_s = now_s
            return None
        if now_s - self._last_adjust_s < self.interval_s \
                or self.ema_ms is None:
            return None
        self._last_adjust_s = now_s
        current = engine.decode_chunk
        want = current
        if self.ema_ms > self.target_ms and current > 1:
            want = max(1, current // 2)
        elif (self.ema_ms < self.recover_frac * self.target_ms
              and current < engine.decode_chunk_max):
            want = current * 2
        if want == current:
            return None
        applied = engine.set_decode_chunk(want)
        self.trajectory.append({
            "t_s": round(now_s, 3),
            "ttft_ema_ms": round(self.ema_ms, 1),
            "target_ms": self.target_ms,
            "chunk": applied,
        })
        return applied
