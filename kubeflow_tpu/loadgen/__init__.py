"""Trace-driven production-traffic scenario suite (ROADMAP #4).

Turns "millions of users" from a north-star phrase into a measured,
floor-gated artifact: a seeded deterministic trace generator
(`loadgen.trace`), pure per-tenant SLO accounting (`loadgen.slo`), a
scenario runner that replays a trace against the real continuous-batching
engine through the ordinary submit path (`loadgen.runner`), an SLO-aware
decode-chunk / admission control hook (`loadgen.control`), and 4-6 named
committed scenarios (`loadgen.scenarios` + `loadgen/configs/*.json`).

Grounding: "Evaluating Kubernetes Performance for GenAI Inference"
(PAPERS.md) — the workload dimensions a serving platform must prove, not
assert: heterogeneous prompt/output lengths (multi-bucket + chunked
prefill), many-tenant adapter fleets with skewed popularity (S-LoRA),
bursty diurnal arrivals (modulated Poisson), client cancellations and
disconnects, and SLO attainment under all of it.
"""

from kubeflow_tpu.loadgen.control import SLOController, pick_decode_chunk
from kubeflow_tpu.loadgen.http_client import (run_trace_http,
                                              stream_completion)
from kubeflow_tpu.loadgen.runner import run_scenario, run_trace
from kubeflow_tpu.loadgen.scenarios import (SCENARIOS, Scenario,
                                            load_scenario, miniature)
from kubeflow_tpu.loadgen.slo import RequestRecord, summarize
from kubeflow_tpu.loadgen.trace import (Trace, TraceConfig, TraceRequest,
                                        generate_trace, trace_bytes,
                                        trace_sha256)

__all__ = [
    "Trace", "TraceConfig", "TraceRequest", "generate_trace",
    "trace_bytes", "trace_sha256", "RequestRecord", "summarize",
    "run_scenario", "run_trace", "run_trace_http", "stream_completion",
    "SLOController", "pick_decode_chunk",
    "SCENARIOS", "Scenario", "load_scenario", "miniature",
]
