"""Named, committed traffic scenarios.

Each scenario is a JSON file in `loadgen/configs/` — config-as-data so a
scenario is reviewable in a diff and the bench record can echo exactly
what ran. `load_scenario` materializes one; `miniature` rescales it onto
a tiny engine (CPU fast lane) while keeping the scenario's SHAPE — burst
modulation, tenant/adapter skew, cancellation fraction — intact.

The committed fleet (full-scale values sized for the d1024 serving bench
engine: buckets 64/128/256, 8 slots):

- steady            — plain Poisson, heterogeneous lengths, one tenant:
                      the baseline every other scenario is read against.
- diurnal_burst     — modulated Poisson (amplitude 0.9): peak-rate
                      queueing vs trough recovery in one window.
- multi_tenant_lora — 6 tenants (Zipf-skewed) over a 4-adapter S-LoRA
                      fleet, per-tenant share caps + admission quota:
                      the fairness/admission scenario.
- cancellation_storm— half the clients disconnect mid-generation:
                      goodput-under-cancellation and prompt slot reuse.
- slo_chase         — the ttft_target_ms knob live: the SLO controller
                      re-picks decode_chunk under load and commits its
                      trajectory.
- long_tail_mix     — heavy-tailed (bounded-Pareto) prompt/output
                      lengths: the paged-KV A/B scenario — slab HBM is
                      sized for the tail, paged admission turns the
                      stranded difference into concurrency.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any

from kubeflow_tpu.loadgen.trace import TraceConfig

CONFIG_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "configs")


@dataclasses.dataclass(frozen=True)
class Scenario:
    name: str
    description: str
    trace: TraceConfig
    tenant_max_active: int = 0     # engine.set_tenant_limits knobs
    tenant_max_queued: int = 0
    slo_chase: bool = False
    ttft_target_ms: float = 300.0
    control_interval_s: float = 5.0
    #: committed chaos fault script (chaos/configs/) replayed against the
    #: serving plane alongside the trace; requires a supervised engine
    fault_script: str | None = None

    def to_json(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)
        d["trace"] = self.trace.to_json()
        return d

    def replace(self, **kw) -> "Scenario":
        return dataclasses.replace(self, **kw)


def _names() -> list[str]:
    return sorted(f[:-5] for f in os.listdir(CONFIG_DIR)
                  if f.endswith(".json"))


#: the committed scenario fleet (derived from configs/, so the registry
#: can never drift from the files)
SCENARIOS: tuple[str, ...] = tuple(_names())


def load_scenario(name: str, **trace_overrides: Any) -> Scenario:
    """Load a committed scenario; `trace_overrides` replace TraceConfig
    fields (e.g. vocab=..., seed=...) without touching the file."""
    path = os.path.join(CONFIG_DIR, f"{name}.json")
    if not os.path.exists(path):
        raise KeyError(f"unknown scenario {name!r}; "
                       f"committed: {list(SCENARIOS)}")
    with open(path) as f:
        d = json.load(f)
    trace = TraceConfig.from_json(d["trace"])
    if trace_overrides:
        trace = trace.replace(**trace_overrides)
    return Scenario(
        name=d["name"], description=d.get("description", ""),
        trace=trace,
        tenant_max_active=int(d.get("tenant_max_active", 0)),
        tenant_max_queued=int(d.get("tenant_max_queued", 0)),
        slo_chase=bool(d.get("slo_chase", False)),
        ttft_target_ms=float(d.get("ttft_target_ms", 300.0)),
        control_interval_s=float(d.get("control_interval_s", 5.0)),
        fault_script=d.get("fault_script"))


def miniature(scenario: Scenario, *, vocab: int, max_prompt_len: int,
              duration_s: float = 4.0, rate_rps: float | None = None,
              max_output: int = 8) -> Scenario:
    """Shrink a scenario onto a tiny engine while preserving its shape:
    prompt-length mixture rescaled proportionally into
    [1, max_prompt_len], output budgets clamped, window shortened, burst
    period scaled with the window so the trace still sees full cycles.
    Used by the fast lane and the CPU bench path."""
    t = scenario.trace
    if t.n_templates:
        # shared_prefix family: the effective longest prompt is the
        # longest template plus every turn's user chunk — that is what
        # must fit max_prompt_len, and template/turn lengths scale
        # together so the share-vs-fresh ratio (what the cache-hit
        # numbers mean) survives the shrink
        orig_max = (t.template_len[1]
                    + t.turns[1] * t.turn_user_len[1])
    elif t.long_tail:
        orig_max = t.tail_prompt_len[1]
    else:
        orig_max = max(hi for _, hi, _ in t.prompt_len_mix)
    scale = max_prompt_len / orig_max
    mix = tuple((max(1, int(lo * scale)),
                 max(1, int(hi * scale)), w)
                for lo, hi, w in t.prompt_len_mix)
    dur_scale = duration_s / t.duration_s
    mini = t.replace(
        duration_s=duration_s,
        base_rate_rps=rate_rps if rate_rps is not None
        else t.base_rate_rps,
        burst_period_s=max(0.5, t.burst_period_s * dur_scale),
        prompt_len_mix=mix,
        output_len=(min(t.output_len[0], max_output),
                    min(t.output_len[1], max_output)),
        vocab=vocab,
        cancel_after_s=(t.cancel_after_s[0] * dur_scale,
                        max(t.cancel_after_s[0] * dur_scale,
                            t.cancel_after_s[1] * dur_scale)),
    )
    if t.n_templates:
        mini = mini.replace(
            template_len=(max(1, int(t.template_len[0] * scale)),
                          max(1, int(t.template_len[1] * scale))),
            turn_user_len=(max(1, int(t.turn_user_len[0] * scale)),
                           max(1, int(t.turn_user_len[1] * scale))),
            turn_gap_s=(t.turn_gap_s[0] * dur_scale,
                        max(t.turn_gap_s[0] * dur_scale,
                            t.turn_gap_s[1] * dur_scale)),
        )
    if t.long_tail:
        # the Pareto SHAPE (alpha) survives untouched — only the
        # bounded support rescales, so the short/long imbalance the
        # scenario exists to exercise is intact on the tiny engine
        mini = mini.replace(
            tail_prompt_len=(max(1, int(t.tail_prompt_len[0] * scale)),
                             max(1, int(t.tail_prompt_len[1] * scale))),
            tail_output_len=(min(t.tail_output_len[0], max_output),
                             min(t.tail_output_len[1], max_output)),
        )
    return scenario.replace(trace=mini,
                            control_interval_s=max(
                                0.5, scenario.control_interval_s
                                * dur_scale))
