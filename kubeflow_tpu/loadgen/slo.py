"""Per-tenant SLO accounting — pure math over replay records.

Deliberately engine-free: the runner produces `RequestRecord`s and this
module reduces them, so the arithmetic is verifiable against a
hand-computed miniature trace (tests/test_loadgen_runner.py does exactly
that). Definitions, chosen to be computable by hand:

- TTFT = first_token_s - arrival_s: measured from the SCHEDULED arrival
  (an arrival submitted late because the engine was busy still waited —
  same convention as bench._poisson_run).
- TPOT = (finish_s - first_token_s) / (n_tokens - 1) for n_tokens >= 2.
- A request MEETS SLO iff it completed normally ("stop"/"length"),
  TTFT <= ttft_slo_ms, and (n_tokens < 2 or TPOT <= tpot_slo_ms).
- slo_attainment = met / (offered - client_cancelled): rejected requests
  count against the tenant's attainment (admission failures are SLO
  misses from the client's view); requests the CLIENT abandoned are
  excluded from the denominator (their outcome was the client's choice).
- throughput counts every delivered token (including partial output of
  cancelled requests); goodput counts only tokens of SLO-met requests —
  the gap between the two is the cancellation-storm / SLO-miss waste.
- saturation = delivered_tokens / offered_tokens (demand coverage).
- fairness (aggregate): Jain's index and the max-min ratio over
  per-tenant service ratios (delivered/offered), tenants with demand
  only. 1.0 = perfectly even service relative to demand.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterable, Sequence

import numpy as np


@dataclasses.dataclass(frozen=True)
class RequestRecord:
    """Outcome of one replayed trace request (times relative to run
    start, seconds)."""
    index: int
    tenant: str
    arrival_s: float
    max_new_tokens: int
    adapter: str | None = None
    submit_s: float | None = None       # None = never reached the engine
    first_token_s: float | None = None
    finish_s: float | None = None
    n_tokens: int = 0
    #: stop|length|cancelled|rejected|unsubmitted — "rejected" means
    #: admission control fired; "unsubmitted" means the replay's wall
    #: budget ran out first (only on timed_out runs). Both count against
    #: SLO attainment; only "rejected" counts in the rejected column.
    finish_reason: str = "rejected"
    client_cancelled: bool = False      # the trace said the client left

    @property
    def rejected(self) -> bool:
        return self.finish_reason == "rejected"

    @property
    def completed(self) -> bool:
        return self.finish_reason in ("stop", "length")

    def ttft_ms(self) -> float | None:
        if self.first_token_s is None:
            return None
        return (self.first_token_s - self.arrival_s) * 1e3

    def tpot_ms(self) -> float | None:
        if (self.first_token_s is None or self.finish_s is None
                or self.n_tokens < 2):
            return None
        return ((self.finish_s - self.first_token_s)
                / (self.n_tokens - 1)) * 1e3

    def meets_slo(self, ttft_slo_ms: float, tpot_slo_ms: float) -> bool:
        return request_meets(self.ttft_ms(), self.tpot_ms(),
                             ttft_slo_ms=ttft_slo_ms,
                             tpot_slo_ms=tpot_slo_ms,
                             completed=self.completed)


def request_meets(ttft_ms: float | None, tpot_ms: float | None, *,
                  ttft_slo_ms: float, tpot_slo_ms: float,
                  completed: bool = True) -> bool:
    """THE SLO predicate (module docstring bullet 3), shared by the
    offline record reduction above and the online burn tracker
    (obs/slo.py) so the two surfaces can never drift: completed
    normally, TTFT within bound, and TPOT within bound when defined
    (single-token requests have no TPOT)."""
    if not completed:
        return False
    if ttft_ms is None or ttft_ms > ttft_slo_ms:
        return False
    return tpot_ms is None or tpot_ms <= tpot_slo_ms


def _pct(vals: Sequence[float], q: float) -> float | None:
    if not vals:
        return None
    return round(float(np.percentile(np.asarray(vals, np.float64), q)), 3)


def _tenant_summary(recs: list[RequestRecord], ttft_slo_ms: float,
                    tpot_slo_ms: float, duration_s: float
                    ) -> dict[str, Any]:
    offered = len(recs)
    client_cancelled = sum(r.client_cancelled for r in recs)
    rejected = sum(r.rejected for r in recs)
    completed = sum(r.completed for r in recs)
    met = sum(r.meets_slo(ttft_slo_ms, tpot_slo_ms) for r in recs)
    delivered = sum(r.n_tokens for r in recs)
    offered_tok = sum(r.max_new_tokens for r in recs)
    good_tok = sum(r.n_tokens for r in recs
                   if r.meets_slo(ttft_slo_ms, tpot_slo_ms))
    ttfts = [t for r in recs if (t := r.ttft_ms()) is not None]
    tpots = [t for r in recs if (t := r.tpot_ms()) is not None]
    denom = offered - client_cancelled
    return {
        "offered": offered,
        "completed": completed,
        "rejected": rejected,
        "client_cancelled": client_cancelled,
        "slo_met": met,
        "slo_attainment": round(met / denom, 4) if denom else None,
        "ttft_p50_ms": _pct(ttfts, 50),
        "ttft_p95_ms": _pct(ttfts, 95),
        "tpot_p50_ms": _pct(tpots, 50),
        "tokens_delivered": delivered,
        "tokens_offered": offered_tok,
        "service_ratio": (round(delivered / offered_tok, 4)
                          if offered_tok else None),
        "goodput_tok_per_s": round(good_tok / duration_s, 2),
        "throughput_tok_per_s": round(delivered / duration_s, 2),
    }


def jain_index(xs: Sequence[float]) -> float | None:
    """Jain's fairness index: (Σx)² / (n·Σx²); 1.0 = perfectly even,
    1/n = one party gets everything."""
    xs = [float(x) for x in xs]
    if not xs:
        return None
    sq = sum(x * x for x in xs)
    if sq == 0:
        return 1.0   # nobody got anything: even, in the degenerate sense
    return round(sum(xs) ** 2 / (len(xs) * sq), 4)


def summarize(records: Iterable[RequestRecord], *, ttft_slo_ms: float,
              tpot_slo_ms: float, duration_s: float) -> dict[str, Any]:
    """Reduce replay records into the committed scenario summary:
    per-tenant SLO table + aggregate fairness/saturation/goodput."""
    recs = list(records)
    by_tenant: dict[str, list[RequestRecord]] = {}
    for r in recs:
        by_tenant.setdefault(r.tenant, []).append(r)
    per_tenant = {t: _tenant_summary(rs, ttft_slo_ms, tpot_slo_ms,
                                     duration_s)
                  for t, rs in sorted(by_tenant.items())}
    ratios = [s["service_ratio"] for s in per_tenant.values()
              if s["service_ratio"] is not None]
    # ONE code path for the shared arithmetic: the aggregate is the
    # all-records tenant summary under its committed key names, plus the
    # cross-tenant fairness that only exists at this level — so the
    # attainment/goodput definitions can never diverge between tables
    whole = _tenant_summary(recs, ttft_slo_ms, tpot_slo_ms, duration_s)
    aggregate = {
        "n_requests": whole["offered"],
        "completed": whole["completed"],
        "rejected": whole["rejected"],
        "client_cancelled": whole["client_cancelled"],
        "slo_attainment": whole["slo_attainment"],
        "ttft_p50_ms": whole["ttft_p50_ms"],
        "ttft_p95_ms": whole["ttft_p95_ms"],
        "throughput_tok_per_s": whole["throughput_tok_per_s"],
        "goodput_tok_per_s": whole["goodput_tok_per_s"],
        "saturation": whole["service_ratio"],
        "fairness_jain": jain_index(ratios),
        "fairness_min_over_max": (
            round(min(ratios) / max(ratios), 4)
            if ratios and max(ratios) > 0 else None),
        "slo": {"ttft_ms": ttft_slo_ms, "tpot_ms": tpot_slo_ms},
    }
    return {"aggregate": aggregate, "per_tenant": per_tenant}
