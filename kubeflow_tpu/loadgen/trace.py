"""Seeded, deterministic production-traffic trace generator.

A trace is the full client-side story of a workload window: WHO arrives
(tenant, adapter), WHEN (modulated-Poisson arrivals — steady or bursty
diurnal), WITH WHAT (prompt token ids drawn across a heterogeneous length
mixture, per-request output budgets), and WHETHER THE CLIENT STAYS (a
cancellation/disconnect delay for the abandoning fraction). The runner
replays it against a real engine; the SLO math consumes the outcome.

Determinism is a hard contract: the same `TraceConfig` (same seed)
produces a BYTE-IDENTICAL trace in any process on any platform and with
any library versions — every draw derives from a self-contained
splitmix64 stream (`_SplitMix`, the same finalizer the serving seed fold
uses; numpy Generator distribution streams are explicitly NOT versioned
across numpy releases, so they cannot back a committed-artifact
contract), every libm-dependent comparison (thinning acceptance, Zipf
cumulative weights) is quantized before use so last-ulp sin/log/pow
differences between platforms cannot flip a decision, floats are rounded
at generation time, and `trace_bytes` serializes canonically (sorted
keys, no whitespace). Tests pin the cross-process sha256.

Arrival model: inhomogeneous Poisson via Lewis-Shedler thinning at
rate(t) = base_rate_rps * (1 + burst_amplitude * sin(2π t / burst_period_s
+ burst_phase)); burst_amplitude 0 is plain Poisson. Tenant and adapter
popularity are Zipf-skewed (weight ∝ 1/rank^skew) — the many-user fleets
this suite exists to exercise are never uniform.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
from typing import Any, Sequence

_MASK64 = (1 << 64) - 1


class _SplitMix:
    """Self-contained 64-bit PRNG (splitmix64) with the handful of
    inverse-CDF draws the generator needs. Exists so the byte-identity
    contract depends on NOTHING but this file: numpy's Generator
    distribution methods are exempt from stream-stability guarantees
    across numpy releases, which would silently invalidate committed
    trace_sha256 evidence on an environment bump."""

    def __init__(self, seed: int):
        self._s = int(seed) & _MASK64

    def _next(self) -> int:
        self._s = (self._s + 0x9E3779B97F4A7C15) & _MASK64
        z = self._s
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _MASK64
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _MASK64
        return (z ^ (z >> 31)) & _MASK64

    def random(self) -> float:
        """Uniform in [0, 1) with 53 bits — exact in a double, so the
        value is bit-identical everywhere (pure integer ops + one exact
        scale)."""
        return (self._next() >> 11) * (1.0 / (1 << 53))

    def exponential(self, scale: float) -> float:
        return -math.log(1.0 - self.random()) * scale

    def uniform(self, lo: float, hi: float) -> float:
        return lo + (hi - lo) * self.random()

    def integers(self, lo: int, hi: int) -> int:
        """Uniform integer in [lo, hi) via rejection-free modulo of a
        64-bit draw (bias < 2^-40 for any range here; exact integer
        ops, so platform-stable)."""
        return lo + self._next() % (hi - lo)

    def choice(self, cum_weights: Sequence[float]) -> int:
        """Index into a quantized cumulative-weight table (see
        _cum_weights — quantization happens THERE, once)."""
        u = self.random()
        for i, c in enumerate(cum_weights):
            if u < c:
                return i
        return len(cum_weights) - 1


@dataclasses.dataclass(frozen=True)
class TraceRequest:
    """One replayable client request. `session` is the stable affinity
    key of the multi-turn conversation this request belongs to (the
    shared_prefix family sets it; None elsewhere). The in-process
    scenario runner drives a single engine, where reuse is purely
    content-based — session rides the trace for HTTP-fleet replays
    (set it as the request's `session` body field / X-Session-Key so
    the router's rendezvous affinity sees the grouping; the affinity
    path itself is exercised by tests/test_router_health.py)."""
    index: int
    arrival_s: float            # offset from trace start
    tenant: str
    adapter: str | None
    prompt: tuple[int, ...]
    max_new_tokens: int
    cancel_after_s: float | None  # client disconnect delay; None = stays
    session: str | None = None

    def to_json(self) -> dict[str, Any]:
        d = {"i": self.index, "t": self.arrival_s, "tenant": self.tenant,
             "adapter": self.adapter, "prompt": list(self.prompt),
             "max_new": self.max_new_tokens,
             "cancel_after": self.cancel_after_s}
        if self.session is not None:
            # emitted only when set: traces predating the shared_prefix
            # family keep their committed byte-identity (sha256 pins)
            d["session"] = self.session
        return d

    @staticmethod
    def from_json(d: dict[str, Any]) -> "TraceRequest":
        return TraceRequest(d["i"], d["t"], d["tenant"], d["adapter"],
                            tuple(d["prompt"]), d["max_new"],
                            d["cancel_after"], d.get("session"))


@dataclasses.dataclass(frozen=True)
class TraceConfig:
    """Everything the generator needs; every field feeds the byte-identity
    hash, so two configs that differ anywhere produce different traces."""
    seed: int = 0
    duration_s: float = 30.0
    base_rate_rps: float = 2.0
    burst_amplitude: float = 0.0     # 0..1; 0 = plain Poisson
    burst_period_s: float = 20.0
    burst_phase: float = 0.0         # radians
    n_tenants: int = 1
    tenant_skew: float = 1.2         # Zipf exponent over tenant ranks
    adapters: tuple[str, ...] = ()   # () = base model only
    adapter_skew: float = 1.2
    adapter_none_frac: float = 0.25  # fraction of requests on the base
    # prompt-length mixture: (lo, hi, weight) inclusive integer ranges —
    # heterogeneous lengths are what exercise multi-bucket/chunked prefill
    prompt_len_mix: tuple[tuple[int, int, float], ...] = (
        (4, 48, 0.5), (48, 120, 0.3), (120, 240, 0.2))
    output_len: tuple[int, int] = (16, 64)   # inclusive uniform range
    vocab: int = 32000               # prompt ids drawn from [1, vocab)
    cancel_frac: float = 0.0         # fraction of clients that abandon
    cancel_after_s: tuple[float, float] = (0.2, 2.0)
    ttft_slo_ms: float = 2000.0      # SLO targets the accounting applies
    tpot_slo_ms: float = 500.0
    # -- shared_prefix / multi-turn chat family (the kvcache tentpole's
    # honest workload): n_templates > 0 switches arrivals to SESSIONS —
    # each arrival picks a conversation template (Zipf-skewed over
    # n_templates pre-drawn token sequences of template_len tokens: the
    # system-prompt / few-shot preamble every turn shares), then runs
    # `turns` chat turns. Turn k's prompt is template ++ the
    # accumulated per-turn context (turn_user_len tokens each — the
    # client resending its conversation history), so every later turn
    # extends an earlier prompt exactly the way a radix prefix cache
    # can reuse; turns within a session are spaced by turn_gap_s.
    # Requests carry session="s<arrival_index>" for affinity routing.
    n_templates: int = 0
    template_len: tuple[int, int] = (32, 96)
    template_skew: float = 1.1
    turns: tuple[int, int] = (1, 1)
    turn_user_len: tuple[int, int] = (8, 32)
    turn_gap_s: tuple[float, float] = (0.5, 2.0)
    # -- long_tail family (the paged-KV tentpole's honest workload):
    # long_tail=True replaces BOTH uniform length draws with bounded
    # Pareto (power-law) ones — most requests are short, a heavy tail
    # is 10-50x longer. Exactly the shape that strands slab HBM (every
    # slot is sized for the tail) and that paged block ownership turns
    # into oversubscribed concurrency. tail_alpha is the prompt shape,
    # tail_output_alpha the output-budget shape (lower = heavier);
    # supports are the inclusive [lo, hi] bounds.
    long_tail: bool = False
    tail_alpha: float = 1.1
    tail_prompt_len: tuple[int, int] = (4, 480)
    tail_output_alpha: float = 1.3
    tail_output_len: tuple[int, int] = (1, 256)

    #: shared_prefix-family fields, emitted in to_json only when the
    #: family is enabled: configs (and thus traces) predating it keep
    #: their committed byte-identity / sha256 pins
    _FAMILY_FIELDS = ("n_templates", "template_len", "template_skew",
                      "turns", "turn_user_len", "turn_gap_s")
    #: long_tail-family fields, same emission rule (and so the same
    #: byte-identity story) as the shared_prefix family
    _LT_FIELDS = ("long_tail", "tail_alpha", "tail_prompt_len",
                  "tail_output_alpha", "tail_output_len")

    def to_json(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)
        d["adapters"] = list(self.adapters)
        d["prompt_len_mix"] = [list(m) for m in self.prompt_len_mix]
        d["output_len"] = list(self.output_len)
        d["cancel_after_s"] = list(self.cancel_after_s)
        if self.n_templates > 0:
            d["template_len"] = list(self.template_len)
            d["turns"] = list(self.turns)
            d["turn_user_len"] = list(self.turn_user_len)
            d["turn_gap_s"] = list(self.turn_gap_s)
        else:
            for k in self._FAMILY_FIELDS:
                d.pop(k, None)
        if self.long_tail:
            d["tail_prompt_len"] = list(self.tail_prompt_len)
            d["tail_output_len"] = list(self.tail_output_len)
        else:
            for k in self._LT_FIELDS:
                d.pop(k, None)
        return d

    @staticmethod
    def from_json(d: dict[str, Any]) -> "TraceConfig":
        kw = dict(d)
        kw["adapters"] = tuple(kw.get("adapters", ()))
        kw["prompt_len_mix"] = tuple(
            tuple(m) for m in kw["prompt_len_mix"])
        kw["output_len"] = tuple(kw["output_len"])
        kw["cancel_after_s"] = tuple(kw["cancel_after_s"])
        for k in ("template_len", "turns", "turn_user_len", "turn_gap_s",
                  "tail_prompt_len", "tail_output_len"):
            if k in kw:
                kw[k] = tuple(kw[k])
        return TraceConfig(**kw)

    def replace(self, **kw) -> "TraceConfig":
        return dataclasses.replace(self, **kw)


@dataclasses.dataclass(frozen=True)
class Trace:
    config: TraceConfig
    requests: tuple[TraceRequest, ...]

    @property
    def duration_s(self) -> float:
        return self.config.duration_s

    def to_json(self) -> dict[str, Any]:
        return {"version": 1, "config": self.config.to_json(),
                "requests": [r.to_json() for r in self.requests]}

    @staticmethod
    def from_json(d: dict[str, Any]) -> "Trace":
        return Trace(TraceConfig.from_json(d["config"]),
                     tuple(TraceRequest.from_json(r)
                           for r in d["requests"]))


def _cum_weights(weights: Sequence[float]) -> list[float]:
    """Normalized cumulative thresholds, quantized to 9 decimals: the
    weights come from libm pow()/division whose last ulp varies across
    platforms, and an unquantized threshold compared against a uniform
    draw could flip a choice between machines."""
    total = sum(weights)
    cum, acc = [], 0.0
    for w in weights:
        acc += w / total
        cum.append(round(acc, 9))
    cum[-1] = 1.0
    return cum


def _zipf_cum(n: int, skew: float) -> list[float]:
    return _cum_weights([1.0 / (r ** skew) for r in range(1, n + 1)])


def _round6(x: float) -> float:
    """All trace floats are quantized at GENERATION time, so canonical
    JSON round-trips exactly and byte-identity never hinges on repr of a
    full-precision double."""
    return round(float(x), 6)


def _pareto_int(rng: _SplitMix, lo: int, hi: int, alpha: float) -> int:
    """Bounded-Pareto integer draw (inverse CDF) in [lo, hi]. The pow()
    result is quantized before the floor, the same argument as the
    thinning acceptance: a last-ulp libm difference flips the integer
    only when the true value sits within ~1e-16 of a rounding boundary."""
    u = rng.random()
    frac = 1.0 - u * (1.0 - (lo / hi) ** alpha)
    x = round(lo * frac ** (-1.0 / alpha), 6)
    return min(hi, int(x))


def generate_trace(cfg: TraceConfig) -> Trace:
    """Deterministic trace from one seeded PCG64 stream. Draw order is
    part of the format: arrivals first (thinning), then per-request
    fields in a fixed sequence — never reorder without bumping the trace
    version."""
    if cfg.base_rate_rps <= 0 or cfg.duration_s <= 0:
        raise ValueError("base_rate_rps and duration_s must be positive")
    if not 0 <= cfg.burst_amplitude <= 1:
        raise ValueError("burst_amplitude must be in [0, 1]")
    if not 0 <= cfg.cancel_frac <= 1:
        raise ValueError("cancel_frac must be in [0, 1]")
    if cfg.n_tenants < 1:
        raise ValueError("n_tenants must be >= 1")
    if cfg.vocab < 2:
        raise ValueError("vocab must be >= 2")
    for lo, hi, w in cfg.prompt_len_mix:
        if not (1 <= lo <= hi) or w < 0:
            raise ValueError(f"bad prompt_len_mix entry {(lo, hi, w)}")
    if cfg.n_templates < 0:
        raise ValueError("n_templates must be >= 0")
    if cfg.n_templates:
        for name in ("template_len", "turns", "turn_user_len"):
            lo, hi = getattr(cfg, name)
            if not 1 <= lo <= hi:
                raise ValueError(f"bad {name} range {(lo, hi)}")
        if not 0 <= cfg.turn_gap_s[0] <= cfg.turn_gap_s[1]:
            raise ValueError(f"bad turn_gap_s range {cfg.turn_gap_s}")
    if cfg.long_tail:
        if cfg.n_templates:
            raise ValueError(
                "long_tail and shared_prefix families do not compose "
                "(each owns the per-request length draws)")
        if cfg.tail_alpha <= 0 or cfg.tail_output_alpha <= 0:
            raise ValueError("tail alphas must be positive")
        for name in ("tail_prompt_len", "tail_output_len"):
            lo, hi = getattr(cfg, name)
            if not 1 <= lo <= hi:
                raise ValueError(f"bad {name} range {(lo, hi)}")
    rng = _SplitMix(cfg.seed)

    # -- arrivals: Lewis-Shedler thinning against the peak rate
    rate_max = cfg.base_rate_rps * (1.0 + cfg.burst_amplitude)
    arrivals: list[float] = []
    t = 0.0
    while True:
        t += rng.exponential(1.0 / rate_max)
        if t >= cfg.duration_s:
            break
        rate_t = cfg.base_rate_rps * (
            1.0 + cfg.burst_amplitude * math.sin(
                2.0 * math.pi * t / cfg.burst_period_s + cfg.burst_phase))
        # the acceptance ratio is quantized before the compare: sin()
        # (and the log() inside exponential()) differ in the last ulp
        # across libm implementations, and an unquantized near-boundary
        # accept flipping would change the arrival set (and every later
        # draw) between platforms. At 6 decimals a last-ulp (~1e-16
        # relative) difference only matters if the true ratio sits
        # within ~1e-16 of a rounding boundary — ~1e-10 odds per draw,
        # vs certainty without the quantization.
        if rng.random() < round(rate_t / rate_max, 6):
            arrivals.append(t)

    tenant_cum = _zipf_cum(cfg.n_tenants, cfg.tenant_skew)
    adapter_cum = (_zipf_cum(len(cfg.adapters), cfg.adapter_skew)
                   if cfg.adapters else None)
    mix_cum = _cum_weights([w for _, _, w in cfg.prompt_len_mix])

    if cfg.n_templates:
        return Trace(cfg, _shared_prefix_requests(
            cfg, rng, arrivals, tenant_cum, adapter_cum))

    requests = []
    for i, at in enumerate(arrivals):
        tenant = f"t{rng.choice(tenant_cum)}"
        adapter = None
        if cfg.adapters:
            # the draw for "base or adapter" happens EVEN when the result
            # is base-only, keeping the stream alignment independent of
            # the outcome
            use_adapter = rng.random() >= cfg.adapter_none_frac
            a_idx = rng.choice(adapter_cum)
            if use_adapter:
                adapter = cfg.adapters[a_idx]
        if cfg.long_tail:
            # family draw order (part of the format): prompt length,
            # prompt tokens, output budget — one Pareto draw replaces
            # the (bucket, uniform) pair of the base mixture
            plen = _pareto_int(rng, *cfg.tail_prompt_len, cfg.tail_alpha)
            prompt = tuple(rng.integers(1, cfg.vocab)
                           for _ in range(plen))
            max_new = _pareto_int(rng, *cfg.tail_output_len,
                                  cfg.tail_output_alpha)
        else:
            b = rng.choice(mix_cum)
            lo, hi, _ = cfg.prompt_len_mix[b]
            plen = rng.integers(lo, hi + 1)
            prompt = tuple(rng.integers(1, cfg.vocab)
                           for _ in range(plen))
            max_new = rng.integers(cfg.output_len[0],
                                   cfg.output_len[1] + 1)
        cancel = None
        # same alignment rule: both draws always happen
        will_cancel = rng.random() < cfg.cancel_frac
        c_delay = rng.uniform(*cfg.cancel_after_s)
        if will_cancel:
            cancel = _round6(c_delay)
        requests.append(TraceRequest(i, _round6(at), tenant, adapter,
                                     prompt, max_new, cancel))
    return Trace(cfg, tuple(requests))


def _shared_prefix_requests(cfg: TraceConfig, rng: _SplitMix,
                            arrivals: list[float], tenant_cum,
                            adapter_cum) -> tuple[TraceRequest, ...]:
    """The shared_prefix / multi-turn chat family. Draw order (part of
    the byte-identity format — never reorder without bumping the trace
    version): first the n_templates template token sequences, then per
    SESSION (one per Poisson arrival) tenant → adapter pair → template →
    n_turns → per turn (user tokens, max_new, cancel pair, gap). Turn
    k's prompt is the template plus all k user chunks so far, so within
    a session every later prompt is a strict extension of the previous
    one — the property a radix prefix cache reuses and the
    session-affinity router preserves across replicas. Requests are
    globally re-sorted by arrival (sessions interleave) and re-indexed;
    ties keep session order, so arrivals stay sorted and deterministic."""
    templates: list[tuple[int, ...]] = []
    for _ in range(cfg.n_templates):
        tlen = rng.integers(cfg.template_len[0], cfg.template_len[1] + 1)
        templates.append(tuple(rng.integers(1, cfg.vocab)
                               for _ in range(tlen)))
    template_cum = _zipf_cum(cfg.n_templates, cfg.template_skew)
    rows: list[tuple] = []   # (arrival, order, ...request fields)
    order = 0
    for s_idx, at in enumerate(arrivals):
        tenant = f"t{rng.choice(tenant_cum)}"
        adapter = None
        if cfg.adapters:
            # same stream-alignment rule as the base family: both draws
            # always happen, whatever the outcome
            use_adapter = rng.random() >= cfg.adapter_none_frac
            a_idx = rng.choice(adapter_cum)
            if use_adapter:
                adapter = cfg.adapters[a_idx]
        ctx = list(templates[rng.choice(template_cum)])
        n_turns = rng.integers(cfg.turns[0], cfg.turns[1] + 1)
        t_turn = at
        for _ in range(n_turns):
            ulen = rng.integers(cfg.turn_user_len[0],
                                cfg.turn_user_len[1] + 1)
            ctx.extend(rng.integers(1, cfg.vocab) for _ in range(ulen))
            prompt = tuple(ctx)
            max_new = rng.integers(cfg.output_len[0],
                                   cfg.output_len[1] + 1)
            will_cancel = rng.random() < cfg.cancel_frac
            c_delay = rng.uniform(*cfg.cancel_after_s)
            cancel = _round6(c_delay) if will_cancel else None
            rows.append((_round6(t_turn), order, tenant, adapter, prompt,
                         max_new, cancel, f"s{s_idx}"))
            order += 1
            t_turn += rng.uniform(*cfg.turn_gap_s)
    rows.sort(key=lambda r: (r[0], r[1]))
    return tuple(
        TraceRequest(i, at, tenant, adapter, prompt, max_new, cancel,
                     session)
        for i, (at, _o, tenant, adapter, prompt, max_new, cancel,
                session) in enumerate(rows))


def trace_bytes(trace: Trace) -> bytes:
    """Canonical serialization — THE byte-identity artifact (sorted keys,
    no whitespace, generation-time-rounded floats)."""
    return json.dumps(trace.to_json(), sort_keys=True,
                      separators=(",", ":")).encode()


def trace_sha256(trace: Trace) -> str:
    return hashlib.sha256(trace_bytes(trace)).hexdigest()


def tenant_names(trace: Trace) -> list[str]:
    """Distinct tenants in arrival order (stable across runs)."""
    seen: dict[str, None] = {}
    for r in trace.requests:
        seen.setdefault(r.tenant, None)
    return list(seen)


def offered_tokens(trace: Trace, tenants: Sequence[str] | None = None
                   ) -> int:
    """Total output-token demand (the denominator of saturation)."""
    sel = set(tenants) if tenants is not None else None
    return sum(r.max_new_tokens for r in trace.requests
               if sel is None or r.tenant in sel)
