"""Device-resident KV block pool — the single owner of paged KV memory
(ISSUE 19 tentpole).

The slab engine sizes KV by worst case: `[slots, max_len]` rows, so one
2k-token straggler strands `max_len - 2k` tokens of HBM in every other
slot. The paged engine instead draws fixed-size blocks (`block_tokens`
tokens each — the SAME granule as the radix prefix trie, the gcd of the
prefill buckets) from this pool and stitches them into per-slot block
tables; concurrency is then bounded by *tokens actually resident*, not
by `slots x max_len`.

Split of responsibilities:

  - **This module** mints the device buffers (`make_block_pool_buffers`
    — the ONLY sanctioned construction site; scripts/check_dataplane.py
    lints that nothing outside `kvcache/` calls it) and owns the host
    allocator metadata: a free list, per-block reference counts, and
    the free-block watermark the admission valve keys on.
  - **The engine** (serving/paged.py) carries the returned buffers in
    its cache dict (they are donated through every compiled program and
    rebound on return — the pool never holds a device handle after
    construction, so donation stays sound) and asks the pool only for
    block *ids*.
  - **The radix trie** (kvcache/radix.py) stores block ids as payloads
    in paged mode: banking a prefix is a refcount increment, matching
    one is a table splice — zero-copy both ways.

Block 0 is the TRASH sentinel: it is never allocated, every empty table
entry points at it, and every junk write the slab engine aims at
masked-off rows (prefill right-pad, drained decode chunks of finished
slots, positions past a slot's reservation) lands there harmlessly.
Refcounts make sharing safe: a block referenced by a slot table AND by
the radix trie is freed only when the last reference drops.
"""

from __future__ import annotations

import threading
from typing import Any

import numpy as np


def make_block_pool_buffers(n_layers: int, n_blocks: int, block_tokens: int,
                            n_kv_heads: int, head_dim: int, dtype: Any,
                            kv_quantize: str | None = None) -> dict:
    """Mint the pool's device arrays: k/v `[L, N, bt, kv, hd]` (+ f32
    per-token scales `[L, N, bt, kv]` when int8). kvcache-internal —
    everything else goes through `BlockPool.device_buffers()`."""
    import jax.numpy as jnp

    shape = (n_layers, n_blocks, block_tokens, n_kv_heads, head_dim)
    if kv_quantize == "int8":
        sshape = shape[:-1]
        return {"k": jnp.zeros(shape, jnp.int8),
                "v": jnp.zeros(shape, jnp.int8),
                "k_s": jnp.zeros(sshape, jnp.float32),
                "v_s": jnp.zeros(sshape, jnp.float32)}
    if kv_quantize is not None:
        raise ValueError(f"unknown kv_quantize {kv_quantize!r}")
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


class BlockPool:
    """Host-side allocator over a fixed population of device KV blocks.

    Thread-safe (the engine's submit path and scrape hooks race). All
    methods trade in integer block ids; the device payload those ids
    index lives in the engine's cache dict from `device_buffers()` on.
    """

    def __init__(self, n_layers: int, n_blocks: int, block_tokens: int,
                 n_kv_heads: int, head_dim: int, dtype: Any,
                 kv_quantize: str | None = None):
        if n_blocks < 2:
            raise ValueError("n_blocks must be >= 2 (block 0 is the "
                             "trash sentinel)")
        if block_tokens < 1:
            raise ValueError("block_tokens must be >= 1")
        self.n_layers = int(n_layers)
        self.n_blocks = int(n_blocks)
        self.block_tokens = int(block_tokens)
        self.n_kv_heads = int(n_kv_heads)
        self.head_dim = int(head_dim)
        self.dtype = dtype
        self.kv_quantize = kv_quantize
        self._lock = threading.Lock()
        # LIFO free list: recently-freed blocks are re-used first (their
        # junk contents are fully overwritten before any masked read)
        self._free: list[int] = list(range(self.n_blocks - 1, 0, -1))
        self._refs = np.zeros(self.n_blocks, np.int32)
        self._refs[0] = 1          # the sentinel is permanently held
        self._buffers_made = False
        self.allocs = 0
        self.frees = 0
        self.alloc_failures = 0

    # -- device side ---------------------------------------------------------

    def device_buffers(self) -> dict:
        """The pool's device arrays, minted exactly once. The caller
        (the paged engine's cache dict) owns them from here on — the
        pool keeps no handle, so donating them through compiled
        programs never aliases pool state."""
        with self._lock:
            if self._buffers_made:
                raise RuntimeError("BlockPool.device_buffers() is "
                                   "single-shot: the engine cache owns "
                                   "the arrays after construction")
            self._buffers_made = True
        return make_block_pool_buffers(
            self.n_layers, self.n_blocks, self.block_tokens,
            self.n_kv_heads, self.head_dim, self.dtype,
            kv_quantize=self.kv_quantize)

    # -- allocation ----------------------------------------------------------

    @property
    def capacity_blocks(self) -> int:
        """Allocatable blocks (the sentinel excluded)."""
        return self.n_blocks - 1

    @property
    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def watermark_frac(self) -> float:
        """Free fraction of allocatable capacity — the admission
        signal: 1.0 = empty pool, 0.0 = fully committed."""
        cap = self.capacity_blocks
        with self._lock:
            return len(self._free) / cap if cap else 0.0

    def alloc(self, n: int) -> list[int] | None:
        """Take `n` blocks (each at refcount 1), or None — never a
        partial grab — when fewer than `n` are free. The caller runs
        the eviction valve and retries; partial grabs under pressure
        would deadlock two admissions each holding half."""
        if n < 0:
            raise ValueError("alloc count must be >= 0")
        with self._lock:
            if n > len(self._free):
                self.alloc_failures += 1
                return None
            ids = [self._free.pop() for _ in range(n)]
            for b in ids:
                self._refs[b] = 1
            self.allocs += n
            return ids

    def ref(self, ids) -> None:
        """Add one reference to each id (table splice of shared blocks,
        radix banking)."""
        with self._lock:
            for b in ids:
                if not 0 < b < self.n_blocks:
                    raise ValueError(f"block id {b} out of range")
                if self._refs[b] <= 0:
                    raise ValueError(f"ref of free block {b}")
                self._refs[b] += 1

    def deref(self, ids) -> int:
        """Drop one reference from each id; blocks reaching zero return
        to the free list. Returns how many were freed."""
        freed = 0
        with self._lock:
            for b in ids:
                if not 0 < b < self.n_blocks:
                    raise ValueError(f"block id {b} out of range")
                if self._refs[b] <= 0:
                    raise ValueError(f"deref of free block {b}")
                self._refs[b] -= 1
                if self._refs[b] == 0:
                    self._free.append(b)
                    freed += 1
            self.frees += freed
        return freed

    def refcount(self, block_id: int) -> int:
        with self._lock:
            return int(self._refs[block_id])

    # -- observability -------------------------------------------------------

    def stats(self) -> dict[str, Any]:
        cap = self.capacity_blocks
        with self._lock:
            free = len(self._free)
            return {
                "pool_blocks": cap,
                "block_tokens": self.block_tokens,
                "free_blocks": free,
                "used_blocks": cap - free,
                "watermark_frac": round(free / cap, 4) if cap else 0.0,
                "allocs": self.allocs,
                "frees": self.frees,
                "alloc_failures": self.alloc_failures,
            }

    def check_invariants(self) -> None:
        with self._lock:
            free = set(self._free)
            assert len(free) == len(self._free), "duplicate free ids"
            assert 0 not in free, "sentinel on the free list"
            assert self._refs[0] >= 1, "sentinel lost its permanent ref"
            for b in range(1, self.n_blocks):
                held = self._refs[b] > 0
                assert held != (b in free), (
                    f"block {b}: refs={self._refs[b]} free={b in free}")
