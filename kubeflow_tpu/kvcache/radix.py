"""Radix-tree prefix index over token sequences → ref-counted KV blocks.

The fleet-scale KV-reuse core (ROADMAP #4, the SGLang RadixAttention /
vLLM prefix-caching idea, TPU-shaped): at production scale most traffic
shares prefixes — system prompts, few-shot templates, multi-turn chat
context — so the KV a prefill computes for one request is the KV the
next request with the same prefix needs. This module is the INDEX over
that sharing:

  - token sequences are paged into fixed-size blocks of `block_tokens`
    tokens; a cached prefix is a root-to-node chain of blocks in a trie
    whose edges are exact token tuples of one block each (fixed-size
    blocks make every edge the same length, so the "radix" tree
    degenerates into a block-trie — the static-shape form the serving
    engine's compiled-program menu wants);
  - each node owns ONE block payload (opaque to this module: the engine
    stores device KV arrays, tests store anything) plus a reference
    count and an LRU tick;
  - `match()` returns the longest cached block-aligned prefix and PINS
    its chain (ref+1 per block) so eviction can never reclaim KV an
    in-flight prefill is about to consume — the caller releases after
    the dispatch;
  - `insert()` extends chains block-by-block, deduplicating against
    what is already cached (inserting a prompt whose template is cached
    stores only the new suffix blocks), evicting LRU *leaves* with
    refs == 0 to stay under `capacity_blocks` — interior nodes are
    never evicted (that would orphan their descendants' chains), pinned
    nodes are never evicted (the in-use invariant), and when nothing is
    evictable the insert simply stops caching (a cache must degrade,
    never corrupt);
  - per-tenant accounting (hits, misses, reused tokens, inserted /
    evicted blocks) is recorded by explicit `record_hit`/`record_miss`
    calls, NOT inside match(): the engine may match a prefix and then
    find no legal continuation program for it, and that must not count
    as a hit in the committed record.

Deliberately jax-free: payloads are opaque, so the structure and its
invariants are testable in the fast lane with plain Python objects,
and the module is importable by routing/analysis code that never
touches a device.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Sequence

from kubeflow_tpu.obs import metrics as obs_metrics


class Block:
    """One cached block: `payload` is opaque (the engine stores device
    KV arrays — [L, 1, B, kv, hd] slices, quantized when the cache is
    int8), `refs` counts live pins, `tick` is the LRU clock."""

    __slots__ = ("payload", "refs", "tick")

    def __init__(self, payload: Any, tick: int):
        self.payload = payload
        self.refs = 0
        self.tick = tick


class _Node:
    __slots__ = ("key", "parent", "children", "block")

    def __init__(self, key: tuple | None, parent: "_Node | None",
                 block: Block | None):
        self.key = key                      # edge label from parent
        self.parent = parent
        self.children: dict[tuple, _Node] = {}
        self.block = block


class MatchResult:
    """A pinned longest-cached-prefix: `tokens` matched (a multiple of
    block_tokens), `payloads` in chain order. Hold it across the window
    where the payloads must stay alive; `RadixKVCache.release()` unpins.
    Truncating consumption to fewer blocks than matched is fine — the
    pin covers the whole chain either way."""

    __slots__ = ("tokens", "payloads", "_nodes", "_released")

    def __init__(self, tokens: int, payloads: list[Any],
                 nodes: list[_Node]):
        self.tokens = tokens
        self.payloads = payloads
        self._nodes = nodes
        self._released = False

    @property
    def n_blocks(self) -> int:
        return len(self._nodes)


class RadixKVCache:
    """Thread-safe block-granular prefix KV index. See module docstring
    for the invariants; `check_invariants()` asserts them (the property
    tests drive it after every operation)."""

    def __init__(self, block_tokens: int, capacity_blocks: int):
        if block_tokens < 1:
            raise ValueError("block_tokens must be >= 1")
        if capacity_blocks < 1:
            raise ValueError("capacity_blocks must be >= 1")
        self.block_tokens = int(block_tokens)
        self.capacity_blocks = int(capacity_blocks)
        # one root per namespace: the engine namespaces by adapter id —
        # a prefix prefilled through adapter X is WRONG KV for adapter Y
        # even at identical tokens, so the chains must never collide.
        # Capacity and eviction are shared across namespaces.
        self._roots: dict[Any, _Node] = {}
        self._n_blocks = 0
        self._tick = 0
        self._lock = threading.RLock()
        # global + per-tenant accounting; tenant None aggregates under
        # the anonymous "" row so the committed record never carries a
        # null key
        self._acct: dict[str, dict[str, int]] = {}
        self._evicted_blocks = 0
        self._inserted_blocks = 0
        # paged mode (ISSUE 19): payloads are pool block ids, not device
        # arrays. `evict_hook(payload)` runs on every eviction BEFORE the
        # payload is dropped — the paged engine derefs the pool block
        # there, so trie eviction returns HBM to the free list. The
        # attached pool also becomes the source of truth for the
        # free_blocks/watermark_frac gauges in stats().
        self.evict_hook: Callable[[Any], None] | None = None
        self._pool = None

    def attach_pool(self, pool) -> None:
        """Bind the device block pool whose free-block watermark the
        stats() gauges should report (paged engines). Without one, the
        gauges fall back to this trie's own index headroom."""
        with self._lock:
            self._pool = pool

    # -- structure -----------------------------------------------------------

    def _touch(self, node: _Node) -> None:
        self._tick += 1
        node.block.tick = self._tick

    def _root_for(self, namespace: Any, create: bool) -> "_Node | None":
        root = self._roots.get(namespace)
        if root is None and create:
            root = _Node(None, None, None)
            self._roots[namespace] = root
        return root

    def match(self, tokens: Sequence[int], *,
              max_tokens: int | None = None,
              namespace: Any = None) -> MatchResult:
        """Longest cached block-aligned prefix of `tokens`, capped at
        `max_tokens` (the engine passes len(prompt) - 1: at least one
        tail token must remain to produce next-token logits). Pins every
        block on the returned chain and LRU-touches it; ALWAYS pair with
        release(), even for 0-token matches (a no-op there)."""
        bt = self.block_tokens
        limit = len(tokens) if max_tokens is None else min(len(tokens),
                                                           max_tokens)
        with self._lock:
            node = self._root_for(namespace, create=False)
            if node is None:
                return MatchResult(0, [], [])
            nodes: list[_Node] = []
            pos = 0
            while pos + bt <= limit:
                key = tuple(tokens[pos:pos + bt])
                child = node.children.get(key)
                if child is None:
                    break
                nodes.append(child)
                node = child
                pos += bt
            for n in nodes:
                n.block.refs += 1
                self._touch(n)
            return MatchResult(pos, [n.block.payload for n in nodes],
                               nodes)

    def release(self, m: MatchResult) -> None:
        """Unpin a match (idempotent)."""
        with self._lock:
            if m._released:
                return
            m._released = True
            for n in m._nodes:
                n.block.refs -= 1

    def cached_prefix_len(self, tokens: Sequence[int], *,
                          max_tokens: int | None = None,
                          namespace: Any = None) -> int:
        """Unpinned probe: how many leading tokens a match() would
        return right now. Does NOT touch LRU order — probes (submit-time
        reporting, skip-extract checks) must not keep dead entries
        warm."""
        bt = self.block_tokens
        limit = len(tokens) if max_tokens is None else min(len(tokens),
                                                           max_tokens)
        with self._lock:
            node = self._root_for(namespace, create=False)
            if node is None:
                return 0
            pos = 0
            while pos + bt <= limit:
                child = node.children.get(tuple(tokens[pos:pos + bt]))
                if child is None:
                    break
                node = child
                pos += bt
            return pos

    def insert(self, tokens: Sequence[int],
               payload_fn: Callable[[int, int, int], Any], *,
               max_tokens: int | None = None,
               tenant: str | None = None,
               namespace: Any = None) -> int:
        """Cache the block-aligned prefix of `tokens` (up to
        `max_tokens`), extending whatever chain already exists.
        `payload_fn(block_index, start, end)` is called ONLY for blocks
        not already cached — the engine slices device KV lazily, so a
        fully-cached prompt costs zero extraction. Returns the number of
        NEW blocks stored. Stops early (still a valid chain — a prefix
        of a prefix is a prefix) when capacity is exhausted and nothing
        is evictable."""
        bt = self.block_tokens
        limit = len(tokens) if max_tokens is None else min(len(tokens),
                                                           max_tokens)
        new_blocks = 0
        with self._lock:
            node = self._root_for(namespace, create=True)
            path: set[int] = {id(node)}
            pos = 0
            while pos + bt <= limit:
                key = tuple(tokens[pos:pos + bt])
                child = node.children.get(key)
                if child is None:
                    if self._n_blocks >= self.capacity_blocks \
                            and not self._evict_one(path):
                        break
                    self._tick += 1
                    block = Block(payload_fn(pos // bt, pos, pos + bt),
                                  self._tick)
                    child = _Node(key, node, block)
                    node.children[key] = child
                    self._n_blocks += 1
                    self._inserted_blocks += 1
                    new_blocks += 1
                    self._row(tenant)["inserted_blocks"] += 1
                    obs_metrics.PREFIX_EVENTS.inc(event="insert")
                else:
                    self._touch(child)
                node = child
                path.add(id(node))
                pos += bt
        return new_blocks

    def _evict_one(self, protect: set[int]) -> bool:
        """Reclaim the LRU evictable leaf: refs == 0, no children, not
        on the current insertion path. O(n) scan — capacities are
        hundreds of blocks, and insert is never on the decode hot
        path. Returns False when nothing is evictable (everything
        pinned or interior): the caller degrades to not caching."""
        victim: _Node | None = None
        stack = list(self._roots.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if (n.block is not None and not n.children
                    and n.block.refs == 0 and id(n) not in protect
                    and (victim is None or n.block.tick
                         < victim.block.tick)):
                victim = n
        if victim is None:
            return False
        del victim.parent.children[victim.key]
        if self.evict_hook is not None:
            self.evict_hook(victim.block.payload)
        victim.block.payload = None   # drop the device arrays NOW
        self._n_blocks -= 1
        self._evicted_blocks += 1
        obs_metrics.PREFIX_EVENTS.inc(event="evict")
        return True

    def evict(self, n_blocks: int) -> int:
        """The admission PRESSURE VALVE (ISSUE 19): forcibly evict up to
        `n_blocks` LRU unpinned leaves — with an `evict_hook` attached,
        each eviction derefs its pool block, so this is how an
        oversubscribed paged engine turns cached-but-idle prefix KV back
        into admission headroom (the evicted prefix is recomputable from
        tokens; the radix parity contract keeps the recompute
        byte-identical). Returns how many blocks were evicted — fewer
        than asked when everything left is pinned or interior."""
        freed = 0
        with self._lock:
            while freed < n_blocks and self._evict_one(set()):
                freed += 1
        return freed

    # -- accounting ----------------------------------------------------------

    def _row(self, tenant: str | None) -> dict[str, int]:
        key = tenant if tenant is not None else ""
        row = self._acct.get(key)
        if row is None:
            row = {"hits": 0, "misses": 0, "reused_tokens": 0,
                   "inserted_blocks": 0}
            self._acct[key] = row
        return row

    def record_hit(self, tenant: str | None, reused_tokens: int) -> None:
        """One admission reused `reused_tokens` of cached prefix KV.
        Called by the engine AFTER it committed to the continuation
        dispatch — a match the engine could not use is a miss."""
        with self._lock:
            row = self._row(tenant)
            row["hits"] += 1
            row["reused_tokens"] += reused_tokens
        obs_metrics.PREFIX_EVENTS.inc(event="hit")

    def record_miss(self, tenant: str | None) -> None:
        with self._lock:
            self._row(tenant)["misses"] += 1
        obs_metrics.PREFIX_EVENTS.inc(event="miss")

    @property
    def n_blocks(self) -> int:
        with self._lock:
            return self._n_blocks

    def stats(self) -> dict[str, Any]:
        """The committed-record shape: global counters + per-tenant
        rows. hit_rate is over recorded hits+misses (admissions the
        engine considered), not raw match calls. pinned_blocks /
        evictable_blocks are live occupancy gauges (the disagg
        backpressure + /healthz surface): pinned = refs > 0 (an
        in-flight admission or handoff holds the chain), evictable =
        unpinned LEAVES the next insert could reclaim — capacity minus
        blocks plus evictable is what the pool can still absorb."""
        with self._lock:
            hits = sum(r["hits"] for r in self._acct.values())
            misses = sum(r["misses"] for r in self._acct.values())
            reused = sum(r["reused_tokens"] for r in self._acct.values())
            pinned = evictable = 0
            stack = list(self._roots.values())
            while stack:
                n = stack.pop()
                stack.extend(n.children.values())
                if n.block is None:
                    continue
                if n.block.refs > 0:
                    pinned += 1
                elif not n.children:
                    evictable += 1
            # free_blocks / watermark_frac: the ADMISSION gauges
            # (ISSUE 19). With a device pool attached (paged engines)
            # they report the pool's free-block watermark — the signal
            # the oversubscribed admission gate keys on; otherwise they
            # degrade to this trie's own index headroom.
            if self._pool is not None:
                free = self._pool.free_blocks
                cap = self._pool.capacity_blocks
            else:
                free = self.capacity_blocks - self._n_blocks
                cap = self.capacity_blocks
            return {
                "block_tokens": self.block_tokens,
                "capacity_blocks": self.capacity_blocks,
                "blocks": self._n_blocks,
                "pinned_blocks": pinned,
                "evictable_blocks": evictable,
                "free_blocks": free,
                "watermark_frac": round(free / cap, 4) if cap else 0.0,
                "hits": hits,
                "misses": misses,
                "hit_rate": (round(hits / (hits + misses), 4)
                             if hits + misses else None),
                "reused_tokens": reused,
                "inserted_blocks": self._inserted_blocks,
                "evicted_blocks": self._evicted_blocks,
                "per_tenant": {k: dict(v)
                               for k, v in sorted(self._acct.items())},
            }

    def clear(self) -> None:
        """Drop every unpinned block (close()/reset path). Pinned blocks
        survive — their chains re-root under a fresh tree is NOT
        attempted; callers must have released all matches first."""
        with self._lock:
            pinned = sum(self._pinned_count(r)
                         for r in self._roots.values())
            if pinned:
                raise RuntimeError(
                    f"clear() with {pinned} pinned blocks outstanding")
            self._roots = {}
            self._n_blocks = 0

    def _pinned_count(self, node: _Node) -> int:
        n = (1 if node.block is not None and node.block.refs > 0 else 0)
        return n + sum(self._pinned_count(c)
                       for c in node.children.values())

    # -- invariants (property tests drive this after every op) ---------------

    def check_invariants(self) -> None:
        with self._lock:
            count = 0
            roots = set(map(id, self._roots.values()))
            stack = list(self._roots.values())
            while stack:
                n = stack.pop()
                stack.extend(n.children.values())
                if id(n) in roots:
                    assert n.block is None
                    continue
                count += 1
                assert n.block is not None and n.block.refs >= 0
                assert n.block.payload is not None, \
                    "evicted block still reachable"
                assert len(n.key) == self.block_tokens
                assert n.parent.children[n.key] is n
            assert count == self._n_blocks, (count, self._n_blocks)
            assert count <= self.capacity_blocks


class StageMatchResult:
    """Pinned longest-prefix across EVERY stage's chain (the pp-aware
    twin of MatchResult): `tokens` = the SHORTEST per-stage match (a
    block is only usable when all stages still hold it — uneven
    eviction truncates to the common prefix), `payloads[i]` = the tuple
    of per-stage payloads for block i. Release through
    StagePartitionedKVCache.release."""

    __slots__ = ("tokens", "payloads", "_inner")

    def __init__(self, tokens: int, payloads: list[tuple],
                 inner: list[MatchResult]):
        self.tokens = tokens
        self.payloads = payloads
        self._inner = inner

    @property
    def n_blocks(self) -> int:
        return len(self.payloads)


class StagePartitionedKVCache:
    """Stage-aware view over ONE RadixKVCache for pp-sharded serving
    (ISSUE 14): every logical KV block exists once PER PIPELINE STAGE —
    stage s's slice of the [L, ...] rows — and the stage id enters the
    block key (namespace (ns, stage)), so KV banked under one stage
    layout can never be handed to another layout or another stage's
    slab. Capacity, eviction, and the LRU clock stay shared in the
    inner cache (a logical block costs n_stages physical blocks — the
    engine scales capacity accordingly); per-tenant insert accounting
    counts stage 0 only, so the committed per-tenant block counts stay
    logical, not multiplied by pp.

    match/insert/cached_prefix_len take the MINIMUM across stages:
    shared-capacity eviction may truncate one stage's chain before
    another's, and a prefix is only reusable where every stage can
    still materialize its slice."""

    def __init__(self, inner: RadixKVCache, n_stages: int):
        if n_stages < 1:
            raise ValueError("n_stages must be >= 1")
        self.inner = inner
        self.n_stages = int(n_stages)

    # -- geometry passthroughs ------------------------------------------------

    @property
    def block_tokens(self) -> int:
        return self.inner.block_tokens

    @property
    def capacity_blocks(self) -> int:
        return self.inner.capacity_blocks

    @property
    def n_blocks(self) -> int:
        return self.inner.n_blocks

    def _ns(self, namespace: Any, stage: int) -> tuple:
        return (namespace, stage)

    # -- the RadixKVCache surface the engine drives ---------------------------

    def match(self, tokens: Sequence[int], *,
              max_tokens: int | None = None,
              namespace: Any = None) -> StageMatchResult:
        ms = [self.inner.match(tokens, max_tokens=max_tokens,
                               namespace=self._ns(namespace, s))
              for s in range(self.n_stages)]
        pos = min(m.tokens for m in ms)
        nb = pos // self.block_tokens
        payloads = [tuple(m.payloads[i] for m in ms) for i in range(nb)]
        return StageMatchResult(pos, payloads, ms)

    def release(self, m: StageMatchResult) -> None:
        for im in m._inner:
            self.inner.release(im)

    def cached_prefix_len(self, tokens: Sequence[int], *,
                          max_tokens: int | None = None,
                          namespace: Any = None) -> int:
        return min(self.inner.cached_prefix_len(
            tokens, max_tokens=max_tokens,
            namespace=self._ns(namespace, s))
            for s in range(self.n_stages))

    def insert(self, tokens: Sequence[int],
               payload_fn: Callable[[int, int, int], Any], *,
               max_tokens: int | None = None,
               tenant: str | None = None,
               namespace: Any = None) -> int:
        """payload_fn(block_index, start, end) must return the TUPLE of
        per-stage payloads for that block (the engine's raw-extract
        already produces per-stage parts); stage s stores element s
        under its own namespace. The tuple is memoized per block index —
        every stage inserts the same new blocks, so without the memo the
        engine would re-slice every stage's parts pp times per block.
        Returns stage 0's new-block count (the logical number of new
        blocks)."""
        memo: dict[int, Any] = {}

        def payload_at(i, a, b):
            if i not in memo:
                memo[i] = payload_fn(i, a, b)
            return memo[i]

        new0 = 0
        for s in range(self.n_stages):
            def payload_s(i, a, b, s=s):
                return payload_at(i, a, b)[s]
            n = self.inner.insert(
                tokens, payload_s, max_tokens=max_tokens,
                tenant=tenant if s == 0 else None,
                namespace=self._ns(namespace, s))
            if s == 0:
                new0 = n
        return new0

    def record_hit(self, tenant: str | None, reused_tokens: int) -> None:
        self.inner.record_hit(tenant, reused_tokens)

    def record_miss(self, tenant: str | None) -> None:
        self.inner.record_miss(tenant)

    def stats(self) -> dict[str, Any]:
        out = self.inner.stats()
        out["stages"] = self.n_stages
        # physical blocks count every stage's copy; the logical view is
        # what capacity planning/debugging wants next to hit rates
        out["logical_blocks"] = out["blocks"] // self.n_stages
        return out

    def clear(self) -> None:
        self.inner.clear()

    def check_invariants(self) -> None:
        self.inner.check_invariants()
