"""Fleet-scale prefix-KV reuse (ROADMAP #4).

`kvcache.radix` is the index: a radix/block-trie over token sequences
mapping to ref-counted, fixed-size KV blocks with LRU eviction that
never reclaims in-use blocks, plus per-tenant reuse accounting. The
serving engine (`serving/llm.py`) owns the device side — extracting
block payloads after prefill, materializing matched chains into the
continuation programs' prefix arrays — and the router
(`serving/router.py`) owns placement: rendezvous-hashed session
affinity so repeat traffic lands on the replica that already holds its
prefix. The loadgen `shared_prefix` trace family measures the whole
loop honestly.
"""

from kubeflow_tpu.kvcache.pool import BlockPool
from kubeflow_tpu.kvcache.radix import (Block, MatchResult, RadixKVCache,
                                        StageMatchResult,
                                        StagePartitionedKVCache)

__all__ = ["Block", "BlockPool", "MatchResult", "RadixKVCache",
           "StageMatchResult", "StagePartitionedKVCache"]
