"""MFU accounting (SURVEY.md §4 "beyond reference": the rebuild adds MFU
tracking the reference never had).

Two FLOP sources: (a) XLA's own cost analysis on the compiled step — exact
for what was actually compiled; (b) analytic per-model formulas
(models.llama.flops_per_token) — stable across compiler versions. Peak chip
FLOPs tables cover the TPU generations this framework targets.
"""

from __future__ import annotations

import jax

# bf16 peak FLOP/s per chip. (v5e's oft-quoted 394 TOPS is int8; bf16 is 197.)
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,  # v5e
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,  # trillium
    "cpu": 1e11,  # nominal, so CPU tests produce finite MFU
}


def device_peak_flops(device: jax.Device | None = None) -> float:
    dev = device if device is not None else jax.devices()[0]
    kind = getattr(dev, "device_kind", "cpu")
    for name, peak in PEAK_FLOPS.items():
        if name.lower() in str(kind).lower():
            return peak
    return PEAK_FLOPS["cpu"]


def compiled_flops(compiled) -> float | None:
    """Total FLOPs of a jax compiled/lowered step via XLA cost analysis."""
    try:
        analysis = compiled.cost_analysis()
        if isinstance(analysis, list):
            analysis = analysis[0]
        return float(analysis.get("flops", 0.0)) or None
    except Exception:
        return None


def mfu(flops_per_step: float, step_time_s: float, n_devices: int,
        peak_per_device: float | None = None) -> float:
    peak = peak_per_device if peak_per_device else device_peak_flops()
    if step_time_s <= 0:
        return 0.0
    return flops_per_step / (step_time_s * peak * n_devices)
