"""Data pipelines: synthetic generators per model family + sharded host→device
staging. The reference delegates data loading entirely to user containers;
here the built-in models get deterministic synthetic datasets (benchmarking,
HPO sweeps, tests) plus an array-backed dataset for real data.

Multi-host note: each process generates/loads only its local shard (determined
by jax.process_index()), and `Trainer.shard_batch` stages it onto the mesh —
the jax.make_array_from_process_local_data path when running multi-process.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterator

import numpy as np


@dataclasses.dataclass
class DatasetConfig:
    """What a job trains on (the `dataset` key of KTPU_TRAINER_CONFIG).

    The reference mounts real data into trainer pods (⊘ kubeflow/examples
    mnist PVC/GCS volumes); here the same contract is a typed source spec:

      synthetic   — per-model generator (default; benches/HPO/tests)
      token_file  — flat uint32 token corpus via the C++ prefetching loader
                    (native/src/data_loader.cpp) with a Python twin fallback
      array_file  — .npz of named arrays, epoch-cycled minibatches

    Multi-host: every process sees the same config; `make_dataset` gives each
    process a batch_size/process_count slice (stride-sliced rows for
    array_file, a process-decorrelated crop seed for token_file/synthetic)
    and `Trainer.shard_batch` assembles the global array.
    """

    type: str = "synthetic"
    path: str | None = None
    seq_len: int = 128
    seed: int | None = None  # falls back to TrainerConfig.seed
    prefer_native: bool = True  # token_file: C++ prefetch ring when built
    shuffle: bool = True  # array_file


def synthetic_tokens(batch_size: int, seq_len: int, vocab_size: int,
                     seed: int = 0) -> Iterator[dict[str, Any]]:
    """Infinite LM batches with a learnable structure (repeating n-grams) so
    loss actually decreases — pure-random tokens can't show learning."""
    rng = np.random.default_rng(seed)
    base = rng.integers(0, vocab_size, size=(64,))
    while True:
        starts = rng.integers(0, 64, size=(batch_size,))
        # exactly seq_len tokens: the model forwards the full sequence and
        # shifts logits internally (loss over seq_len-1 targets), keeping S a
        # clean power of two for attention blocks and the sequence mesh axis
        tokens = np.stack([
            np.resize(np.roll(base, -s), seq_len) for s in starts
        ])
        noise = rng.random(tokens.shape) < 0.02
        tokens = np.where(noise, rng.integers(0, vocab_size, tokens.shape), tokens)
        yield {"tokens": tokens.astype(np.int32)}


def synthetic_images(batch_size: int, image_size: int, channels: int,
                     n_classes: int, seed: int = 0) -> Iterator[dict[str, Any]]:
    """Class-conditional gaussian blobs: learnable image classification."""
    rng = np.random.default_rng(seed)
    protos = rng.normal(size=(n_classes, image_size, image_size, channels))
    while True:
        labels = rng.integers(0, n_classes, size=(batch_size,))
        images = protos[labels] + 0.5 * rng.normal(
            size=(batch_size, image_size, image_size, channels))
        yield {"image": images.astype(np.float32),
               "label": labels.astype(np.int32)}


def synthetic_classification_text(batch_size: int, seq_len: int,
                                  vocab_size: int, n_classes: int = 2,
                                  seed: int = 0) -> Iterator[dict[str, Any]]:
    """BERT-style: label determined by presence of class-marker tokens."""
    rng = np.random.default_rng(seed)
    while True:
        labels = rng.integers(0, n_classes, size=(batch_size,))
        tokens = rng.integers(n_classes + 1, vocab_size,
                              size=(batch_size, seq_len))
        tokens[:, 1] = labels + 1  # marker token after [CLS]
        tokens[:, 0] = 0  # [CLS]
        yield {"tokens": tokens.astype(np.int32),
               "label": labels.astype(np.int32)}


def array_dataset(arrays: dict[str, np.ndarray], batch_size: int,
                  shuffle: bool = True, seed: int = 0,
                  drop_remainder: bool = True) -> Iterator[dict[str, Any]]:
    """Epoch-cycling minibatcher over in-memory arrays (the MNIST/e2e path)."""
    n = len(next(iter(arrays.values())))
    rng = np.random.default_rng(seed)
    while True:
        idx = rng.permutation(n) if shuffle else np.arange(n)
        stop = n - batch_size + 1 if drop_remainder else n
        for i in range(0, stop, batch_size):
            sel = idx[i:i + batch_size]
            yield {k: v[sel] for k, v in arrays.items()}


def for_model(model: str, model_cfg, batch_size: int, seq_len: int = 128,
              seed: int = 0) -> Iterator[dict[str, Any]]:
    """Default synthetic stream for a registered model (bench/HPO/test path)."""
    if model in ("llama", "llama_lora", "mixtral"):
        return synthetic_tokens(batch_size, seq_len, model_cfg.vocab_size, seed)
    if model == "bert":
        return synthetic_classification_text(
            batch_size, min(seq_len, model_cfg.max_seq_len),
            model_cfg.vocab_size, model_cfg.n_classes, seed)
    if model == "mnist_cnn":
        return synthetic_images(batch_size, 28, 1, model_cfg.n_classes, seed)
    if model == "resnet":
        return synthetic_images(batch_size, model_cfg.image_size, 3,
                                model_cfg.n_classes, seed)
    if model in ("nas_cnn", "darts_supernet", "vit"):
        return synthetic_images(batch_size, model_cfg.image_size,
                                model_cfg.in_channels, model_cfg.n_classes,
                                seed)
    raise KeyError(f"no synthetic data recipe for model {model!r}")


def make_dataset(ds: DatasetConfig, model: str, model_cfg, batch_size: int,
                 fallback_seed: int = 0) -> Iterator[dict[str, Any]]:
    """Resolve a DatasetConfig to this process's batch iterator.

    batch_size is the GLOBAL batch (the Trainer.shard_batch contract); each
    process yields its batch_size/process_count share, decorrelated across
    hosts by a process-offset seed (token_file/synthetic) or a stride slice
    of the rows (array_file)."""
    import jax

    pc, pi = jax.process_count(), jax.process_index()
    if batch_size % pc:
        raise ValueError(
            f"batch_size {batch_size} not divisible by {pc} processes")
    local = batch_size // pc
    seed = ds.seed if ds.seed is not None else fallback_seed

    if ds.type == "synthetic":
        return for_model(model, model_cfg, local, seq_len=ds.seq_len,
                         seed=seed + pi)
    if ds.type == "token_file":
        if not ds.path:
            raise ValueError("dataset.type=token_file requires dataset.path")
        from kubeflow_tpu.training.loader import token_file_dataset

        return token_file_dataset(ds.path, local, ds.seq_len,
                                  seed=seed + pi,
                                  prefer_native=ds.prefer_native)
    if ds.type == "array_file":
        if not ds.path:
            raise ValueError("dataset.type=array_file requires dataset.path")
        with np.load(ds.path) as z:
            arrays = {k: z[k][pi::pc] for k in z.files}
        return array_dataset(arrays, local, shuffle=ds.shuffle, seed=seed)
    raise ValueError(f"unknown dataset type {ds.type!r} "
                     "(expected synthetic | token_file | array_file)")
