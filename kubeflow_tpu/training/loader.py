"""Token-corpus data loader: C++ prefetching core + Python twin.

The reference's input pipelines live in native code inside user images
(SURVEY.md §2.6 data-path row); on TPU the host must prep the next batch
while the device runs the current step or the MXU starves. The native
loader (native/src/data_loader.cpp) mmaps a uint32 token corpus and fills
a ring of batch buffers from a worker thread; `PyTokenLoader` implements
the identical xorshift64* crop sequence in numpy for environments without
a toolchain — and for the differential test that pins them together.

Corpus format: a flat binary file of little-endian uint32 token ids (the
simplest possible tokenized-dataset layout; `write_corpus` produces it).
"""

from __future__ import annotations

import ctypes
import os
from typing import Any, Iterator

import numpy as np

_MASK = (1 << 64) - 1


def _xorshift64star(state: int) -> tuple[int, int]:
    """One step of xorshift64*; must match data_loader.cpp bit-for-bit."""
    s = state
    s ^= s >> 12
    s = (s ^ (s << 25)) & _MASK
    s ^= s >> 27
    return s, (s * 2685821657736338717) & _MASK


def write_corpus(path: str, tokens: np.ndarray) -> None:
    tokens = np.ascontiguousarray(tokens, dtype=np.uint32)
    with open(path, "wb") as f:
        f.write(tokens.tobytes())


class PyTokenLoader:
    """Pure-python twin: same batches as the native loader, no prefetch."""

    def __init__(self, path: str, batch_size: int, seq_len: int,
                 seed: int = 0):
        self.batch = batch_size
        self.seq = seq_len
        self._state = seed if seed else 0x9E3779B97F4A7C15
        self.corpus = np.fromfile(path, dtype=np.uint32)
        if len(self.corpus) < seq_len + 1:
            raise ValueError("corpus smaller than one sequence")

    def __iter__(self) -> Iterator[dict[str, Any]]:
        return self

    def __next__(self) -> dict[str, Any]:
        span = len(self.corpus) - self.seq
        rows = np.empty((self.batch, self.seq), np.int32)
        for b in range(self.batch):
            self._state, r = _xorshift64star(self._state)
            start = r % span
            rows[b] = self.corpus[start:start + self.seq].astype(np.int32)
        return {"tokens": rows}

    def close(self) -> None:
        pass


class NativeTokenLoader:
    """ctypes binding over the C++ ring loader. Iterating yields
    {"tokens": int32 [batch, seq]}; the array is a copy (cheap next to the
    device transfer) so the ring buffer can be refilled immediately."""

    def __init__(self, path: str, batch_size: int, seq_len: int,
                 seed: int = 0, n_buffers: int = 3):
        from kubeflow_tpu import native

        self.batch = batch_size
        self.seq = seq_len
        lib = native.library("data_loader")
        lib.dl_open.restype = ctypes.c_void_p
        lib.dl_open.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
                                ctypes.c_int, ctypes.c_uint64,
                                ctypes.c_char_p, ctypes.c_int]
        lib.dl_next.restype = ctypes.c_int
        lib.dl_next.argtypes = [ctypes.c_void_p,
                                ctypes.POINTER(ctypes.POINTER(ctypes.c_int32))]
        lib.dl_release.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.dl_produced.restype = ctypes.c_long
        lib.dl_produced.argtypes = [ctypes.c_void_p]
        lib.dl_corpus_tokens.restype = ctypes.c_long
        lib.dl_corpus_tokens.argtypes = [ctypes.c_void_p]
        lib.dl_close.argtypes = [ctypes.c_void_p]
        self._lib = lib
        err = ctypes.create_string_buffer(256)
        self._h = lib.dl_open(os.fsencode(path), batch_size, seq_len,
                              n_buffers, seed, err, len(err))
        if not self._h:
            raise RuntimeError(f"data_loader: {err.value.decode()}")

    @property
    def corpus_tokens(self) -> int:
        return self._lib.dl_corpus_tokens(self._h)

    @property
    def batches_produced(self) -> int:
        return self._lib.dl_produced(self._h)

    def __iter__(self) -> Iterator[dict[str, Any]]:
        return self

    def __next__(self) -> dict[str, Any]:
        if self._h is None:
            raise StopIteration
        ptr = ctypes.POINTER(ctypes.c_int32)()
        idx = self._lib.dl_next(self._h, ctypes.byref(ptr))
        if idx < 0:
            raise StopIteration
        view = np.ctypeslib.as_array(ptr, shape=(self.batch, self.seq))
        out = np.array(view)  # copy out, then hand the buffer back
        self._lib.dl_release(self._h, idx)
        return {"tokens": out}

    def close(self) -> None:
        if self._h is not None:
            self._lib.dl_close(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - gc path
        try:
            self.close()
        except Exception:
            pass


def token_file_dataset(path: str, batch_size: int, seq_len: int,
                       seed: int = 0, prefer_native: bool = True):
    """Loader over a uint32 token corpus; native (prefetching) when the
    toolchain allows, Python twin otherwise. Both yield identical batches."""
    if prefer_native:
        from kubeflow_tpu.native import NativeUnavailable

        try:
            return NativeTokenLoader(path, batch_size, seq_len, seed)
        except NativeUnavailable:
            pass
    return PyTokenLoader(path, batch_size, seq_len, seed)
