"""Built-in `trainer` pod target — the reference's training container image.

The reference ships example trainer images (⊘ kubeflow/examples mnist,
training-operator `examples/`) that jobs point at; users only write YAML.
Here the same role is a registered worker target: a JAXJob template says

    template:
      backend: thread
      target: trainer
      env:
        KTPU_TRAINER_CONFIG: >
          {"model": "mnist_cnn", "batch_size": 32, "num_steps": 100,
           "optimizer": {"learning_rate": 0.01},
           "mesh": {"data": -1}, "checkpoint_dir": "/tmp/ckpt/mnist"}

and the target builds Trainer + synthetic/array data, trains `num_steps`,
resuming from `checkpoint_dir` if a checkpoint exists (the restart/resume
contract, SURVEY.md §5.4). Metrics go to KTPU_METRICS_FILE (HPO collector)
and, when KTPU_TRIAL_NAME is set, straight to the observation DB.

Cancellation (pod deletion, elastic scale-down) is honored between steps:
the cancel event maps to SystemExit(143) — SIGTERM semantics, retryable
under the ExitCode restart policy.
"""

from __future__ import annotations

import dataclasses
import json
import threading
from typing import Any

from kubeflow_tpu.control.executor import worker_target
from kubeflow_tpu.parallel import MeshConfig
from kubeflow_tpu.training.checkpoint import restore_or_init
from kubeflow_tpu.training.data import DatasetConfig
from kubeflow_tpu.training.metrics_writer import MetricsWriter
from kubeflow_tpu.training.trainer import (OptimizerConfig, Trainer,
                                           TrainerConfig)


def config_from_env(env: dict[str, str]) -> tuple[TrainerConfig, int]:
    """Parse KTPU_TRAINER_CONFIG into (TrainerConfig, num_steps)."""
    raw = json.loads(env.get("KTPU_TRAINER_CONFIG", "{}"))
    num_steps = int(raw.pop("num_steps", 100))
    opt = raw.pop("optimizer", {})
    mesh = raw.pop("mesh", {})
    dataset = raw.pop("dataset", {})
    known = {f.name for f in dataclasses.fields(TrainerConfig)}
    unknown = set(raw) - known
    if unknown:
        raise ValueError(f"unknown trainer config keys: {sorted(unknown)}")
    cfg = TrainerConfig(**raw)
    cfg.optimizer = OptimizerConfig(**opt)
    cfg.mesh = MeshConfig(**mesh)
    cfg.dataset = DatasetConfig(**dataset)
    # LR schedule spans the run unless the spec pinned total_steps itself
    # (e.g. chunked training resuming against a longer schedule)
    if "total_steps" not in opt:
        cfg.optimizer.total_steps = num_steps
    return cfg, num_steps


@worker_target("trainer")
def train_target(env: dict[str, str], cancel: threading.Event) -> None:
    """Train a registered model from env-provided config (see module doc)."""
    from kubeflow_tpu.hpo.observations import report_metric
    from kubeflow_tpu.training import data as data_lib

    cfg, num_steps = config_from_env(env)
    metrics = MetricsWriter(env.get("KTPU_METRICS_FILE"))
    trial = env.get("KTPU_TRIAL_NAME")

    trainer = Trainer(cfg, metrics=metrics)
    state, resumed = restore_or_init(trainer, cfg.checkpoint_dir)
    start = int(state["step"])
    if resumed:
        print(f"resumed from checkpoint at step {start}", flush=True)
    remaining = max(0, num_steps - start)

    def on_step(step: int, scalars: dict[str, Any]) -> None:
        if trial:
            for k, v in scalars.items():
                if k not in ("step_time_s", "includes_compile"):
                    report_metric(trial, k, float(v), step)
        if cancel.is_set():
            raise SystemExit(143)

    data = data_lib.make_dataset(cfg.dataset, cfg.model, trainer.model_cfg,
                                 cfg.batch_size, fallback_seed=cfg.seed)
    try:
        trainer.train(data, remaining, state=state, step_callback=on_step)
    finally:
        if hasattr(data, "close"):
            data.close()
    metrics.close()
    print(f"training done: {num_steps} steps", flush=True)
