"""Checkpoint/resume on orbax (SURVEY.md §5.4).

In the reference, model checkpointing is user-level (torch.save to PVC) and
platform resume = restart policies. Here checkpointing is a framework
guarantee: sharded async orbax saves of {params, opt_state, step}, restored
with the *current* mesh's shardings — so a job restarted on a different
topology (elastic recovery, §5.3) resumes with a resharded state.

Integrity (ISSUE 10 satellite): every step this manager commits gets a
per-file sha256 manifest, written atomically (temp file + fsync + rename
+ directory fsync) AFTER the step's files are hashed — so a torn write,
bit rot, or a truncation between commit and restore is detectable, not
silently restored. `latest_intact_step()` walks steps newest-first,
QUARANTINES any step whose manifest mismatches (moved aside to
`_quarantine/`, out of orbax's step namespace), and falls back to the
newest intact step. A step with NO manifest in a tree that otherwise has
them is treated as partial (a crash mid-commit) and quarantined too;
a tree with no manifests at all is a legacy/foreign checkpoint and the
newest step is trusted as before. The chaos I/O fault hook
(`chaos.injector.io_fault`) is called at the commit points so tests can
truncate a file "mid-write" through a supported seam.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
from typing import Any

import jax
import orbax.checkpoint as ocp

from kubeflow_tpu.chaos.injector import io_fault

MANIFEST_NAME = "ktpu_manifest.json"
QUARANTINE_DIR = "_quarantine"


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _atomic_write_json(path: str, obj: Any) -> None:
    """temp file in the same directory + flush + fsync + rename + dir
    fsync: the manifest either exists complete or not at all — a partial
    manifest would itself be indistinguishable from corruption."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, sort_keys=True, separators=(",", ":"))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path))


def _hash_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            h.update(chunk)
    return h.hexdigest()


def _hash_tree(step_dir: str) -> dict[str, dict[str, Any]]:
    """{relative_path: {sha256, size}} over every file of one committed
    step (the manifest body). The manifest itself is excluded."""
    out: dict[str, dict[str, Any]] = {}
    for root, _dirs, files in os.walk(step_dir):
        for fn in sorted(files):
            if fn == MANIFEST_NAME or fn.endswith(".tmp"):
                continue
            p = os.path.join(root, fn)
            rel = os.path.relpath(p, step_dir)
            out[rel] = {"sha256": _hash_file(p),
                        "size": os.path.getsize(p)}
    return out


def _step_dir(directory: str, step: int) -> str | None:
    """Resolve orbax's on-disk directory for `step` (orbax's default
    layout names it str(step); tolerate padded variants)."""
    cand = os.path.join(directory, str(step))
    if os.path.isdir(cand):
        return cand
    for name in os.listdir(directory):
        p = os.path.join(directory, name)
        # padded layouts: any all-digit name parsing to this step
        # (int("00000000") == 0 covers zero-padded step 0 too)
        if os.path.isdir(p) and name.isdigit() and int(name) == step:
            return p
    return None


def write_step_manifest(directory: str, step: int) -> bool:
    """Hash + atomically commit the manifest for one completed step.
    Returns False when the step's directory does not exist (e.g. orbax
    garbage-collected it past max_to_keep)."""
    step_dir = _step_dir(directory, step)
    if step_dir is None:
        return False
    digests = _hash_tree(step_dir)
    # chaos seams: "checkpoint_commit" runs after hashing (a hook that
    # corrupts a file here models a torn write / bit rot the checksum
    # must catch at restore); "manifest_write" runs before the manifest
    # lands (raising here models a crash mid-commit → a partial step)
    io_fault("checkpoint_commit", step_dir)
    io_fault("manifest_write", os.path.join(step_dir, MANIFEST_NAME))
    _atomic_write_json(os.path.join(step_dir, MANIFEST_NAME),
                       {"version": 1, "step": step, "files": digests})
    return True


def verify_step(directory: str, step: int) -> str:
    """"intact" | "corrupt" | "unmanifested" | "missing" for one step."""
    step_dir = _step_dir(directory, step)
    if step_dir is None:
        return "missing"
    mpath = os.path.join(step_dir, MANIFEST_NAME)
    if not os.path.exists(mpath):
        return "unmanifested"
    try:
        with open(mpath) as f:
            manifest = json.load(f)
        files = manifest["files"]
    except (OSError, ValueError, KeyError):
        return "corrupt"
    try:
        for rel, meta in files.items():
            p = os.path.join(step_dir, rel)
            if not os.path.exists(p) \
                    or os.path.getsize(p) != meta["size"] \
                    or _hash_file(p) != meta["sha256"]:
                return "corrupt"
    except OSError:
        # files vanishing mid-hash = another rank already quarantined
        # this step; report corrupt, the caller's fallback handles it
        return "corrupt"
    # files that APPEARED since the manifest are tolerated (orbax may add
    # bookkeeping); files that vanished or changed are not
    return "intact"


def quarantine_step(directory: str, step: int) -> str | None:
    """Move a corrupt/partial step OUT of orbax's step namespace (into
    `_quarantine/`), so neither orbax nor a later fallback can restore
    it. Returns the quarantine path."""
    step_dir = _step_dir(directory, step)
    if step_dir is None:
        return None
    qroot = os.path.join(directory, QUARANTINE_DIR)
    os.makedirs(qroot, exist_ok=True)
    dest = os.path.join(qroot, os.path.basename(step_dir))
    if os.path.exists(dest):
        shutil.rmtree(dest)
    try:
        os.replace(step_dir, dest)
    except OSError:
        # raced by another rank of a multi-process restore quarantining
        # the same step: the LOSER must keep falling back, not crash in
        # the middle of the corruption-recovery path
        return None
    _fsync_dir(directory)
    return dest


class CheckpointManager:
    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 save_interval_steps: int = 1):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=True,
            ),
        )
        #: steps saved through THIS manager whose manifest is still owed
        #: (saves are async — hashing runs at wait(), after orbax commits)
        self._pending_manifest: set[int] = set()

    def save(self, step: int, state: dict[str, Any], *, force: bool = False) -> bool:
        saved = self._mngr.save(step, args=ocp.args.StandardSave(state),
                                force=force)
        if saved:
            self._pending_manifest.add(step)
        return saved

    def _flush_manifests(self) -> None:
        """Write manifests for every committed-but-unmanifested save.
        Process 0 only under multiprocess checkpointing — every rank
        hashes the same completed tree, one writer avoids the pile-up."""
        if not self._pending_manifest:
            return
        pending, self._pending_manifest = self._pending_manifest, set()
        if jax.process_count() > 1 and jax.process_index() != 0:
            return
        for step in sorted(pending):
            try:
                write_step_manifest(self.directory, step)
            except OSError:
                # a failed commit (disk error, injected fault) leaves the
                # step UNMANIFESTED — in a manifested tree that reads as
                # partial and is quarantined at restore, which is the
                # honest outcome of a commit that did not finish
                pass

    def latest_step(self) -> int | None:
        return self._mngr.latest_step()

    def latest_intact_step(self) -> int | None:
        """Newest step that passes manifest verification; corrupt and
        partial steps are quarantined on the way down (the restore-side
        half of the integrity contract)."""
        self.wait()
        steps = sorted((s for s in self._mngr.all_steps()), reverse=True)
        # hash each step ONCE — verify_step sha256s the whole tree, and
        # at 8B scale a second pass doubles crash-recovery wall time
        statuses = {s: verify_step(self.directory, s) for s in steps}
        has_manifests = any(st not in ("unmanifested", "missing")
                            for st in statuses.values())
        for s in steps:
            status = statuses[s]
            if status == "intact":
                return s
            if status == "unmanifested" and not has_manifests:
                # legacy/foreign tree (pre-manifest checkpoints): trust
                # the newest step, the pre-r9 behavior
                return s
            if status == "missing":
                continue
            # corrupt, or partial in a manifested tree: out of the way
            quarantine_step(self.directory, s)
        return None

    def restore(self, state_like: dict[str, Any], step: int | None = None
                ) -> dict[str, Any]:
        """Restore into the sharding/structure of `state_like` (an abstract or
        concrete state pytree from the current mesh). With no explicit
        step, the newest INTACT step is used — a corrupt/partial newest
        step is quarantined and the restore falls back."""
        step = step if step is not None else self.latest_intact_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        abstract = jax.tree.map(
            lambda x: x if isinstance(x, jax.ShapeDtypeStruct)
            else ocp.utils.to_shape_dtype_struct(x), state_like)
        return self._mngr.restore(step, args=ocp.args.StandardRestore(abstract))

    def wait(self) -> None:
        self._mngr.wait_until_finished()
        self._flush_manifests()

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._flush_manifests()
        self._mngr.close()


def restore_or_init(trainer, directory: str | None):
    """The resume contract: if a checkpoint exists, restore directly into the
    current mesh's shardings (no throwaway random init — at 8B scale a full
    init is ~GBs of wasted HBM traffic); else initialize fresh.
    Returns (state, resumed: bool)."""
    if directory:
        mngr = CheckpointManager(directory)
        step = mngr.latest_intact_step()
        if step is not None:
            restored = mngr.restore(trainer.abstract_state(), step=step)
            mngr.close()
            return restored, True
        mngr.close()
    return trainer.init_state(), False


def restore_params(directory: str, abstract_params, *, step: int | None = None):
    """Restore ONLY the `params` subtree of a trainer checkpoint, placed on
    THIS process's devices (the serving-side restore: no optimizer state,
    and the current topology rather than the training mesh's shardings —
    orbax would otherwise read the training-time sharding file, which is
    unsafe on a different topology).

    Raises FileNotFoundError when the directory holds no checkpoint — a
    configured-but-empty checkpoint must never silently serve random
    weights."""
    import orbax.checkpoint as ocp

    sharding = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding),
        abstract_params)
    with ocp.CheckpointManager(os.path.abspath(directory)) as mngr:
        step = step if step is not None else mngr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {directory!r}")
        try:
            args = ocp.args.PyTreeRestore({"params": abstract},
                                          partial_restore=True)
        except TypeError:
            # older orbax spells partial restore via the legacy transforms
            # API: transforms={} + an explicit restore_args tree drops
            # checkpoint entries (opt_state, step) missing from the item
            restore_args = jax.tree.map(
                lambda x: ocp.ArrayRestoreArgs(sharding=sharding,
                                               global_shape=x.shape,
                                               dtype=x.dtype),
                abstract)
            args = ocp.args.PyTreeRestore(
                {"params": abstract}, transforms={},
                restore_args={"params": restore_args})
        restored = mngr.restore(step, args=args)
    return restored["params"]
