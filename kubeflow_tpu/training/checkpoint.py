"""Checkpoint/resume on orbax (SURVEY.md §5.4).

In the reference, model checkpointing is user-level (torch.save to PVC) and
platform resume = restart policies. Here checkpointing is a framework
guarantee: sharded async orbax saves of {params, opt_state, step}, restored
with the *current* mesh's shardings — so a job restarted on a different
topology (elastic recovery, §5.3) resumes with a resharded state.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import orbax.checkpoint as ocp


class CheckpointManager:
    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 save_interval_steps: int = 1):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=True,
            ),
        )

    def save(self, step: int, state: dict[str, Any], *, force: bool = False) -> bool:
        return self._mngr.save(step, args=ocp.args.StandardSave(state),
                               force=force)

    def latest_step(self) -> int | None:
        return self._mngr.latest_step()

    def restore(self, state_like: dict[str, Any], step: int | None = None
                ) -> dict[str, Any]:
        """Restore into the sharding/structure of `state_like` (an abstract or
        concrete state pytree from the current mesh)."""
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        abstract = jax.tree.map(
            lambda x: x if isinstance(x, jax.ShapeDtypeStruct)
            else ocp.utils.to_shape_dtype_struct(x), state_like)
        return self._mngr.restore(step, args=ocp.args.StandardRestore(abstract))

    def wait(self) -> None:
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()


def restore_or_init(trainer, directory: str | None):
    """The resume contract: if a checkpoint exists, restore directly into the
    current mesh's shardings (no throwaway random init — at 8B scale a full
    init is ~GBs of wasted HBM traffic); else initialize fresh.
    Returns (state, resumed: bool)."""
    if directory:
        mngr = CheckpointManager(directory)
        has_ckpt = mngr.latest_step() is not None
        if has_ckpt:
            restored = mngr.restore(trainer.abstract_state())
            mngr.close()
            return restored, True
        mngr.close()
    return trainer.init_state(), False


def restore_params(directory: str, abstract_params, *, step: int | None = None):
    """Restore ONLY the `params` subtree of a trainer checkpoint, placed on
    THIS process's devices (the serving-side restore: no optimizer state,
    and the current topology rather than the training mesh's shardings —
    orbax would otherwise read the training-time sharding file, which is
    unsafe on a different topology).

    Raises FileNotFoundError when the directory holds no checkpoint — a
    configured-but-empty checkpoint must never silently serve random
    weights."""
    import orbax.checkpoint as ocp

    sharding = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding),
        abstract_params)
    with ocp.CheckpointManager(os.path.abspath(directory)) as mngr:
        step = step if step is not None else mngr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {directory!r}")
        try:
            args = ocp.args.PyTreeRestore({"params": abstract},
                                          partial_restore=True)
        except TypeError:
            # older orbax spells partial restore via the legacy transforms
            # API: transforms={} + an explicit restore_args tree drops
            # checkpoint entries (opt_state, step) missing from the item
            restore_args = jax.tree.map(
                lambda x: ocp.ArrayRestoreArgs(sharding=sharding,
                                               global_shape=x.shape,
                                               dtype=x.dtype),
                abstract)
            args = ocp.args.PyTreeRestore(
                {"params": abstract}, transforms={},
                restore_args={"params": restore_args})
        restored = mngr.restore(step, args=args)
    return restored["params"]
