"""Profiling/tracing hooks — the platform's TensorBoard-profiler analog
(SURVEY.md §5.1: the reference delegates workload profiling to TF/torch
profilers surfaced through the tensorboard-controller; here `jax.profiler`
is first-class and the trace windows are part of the trainer config).

Two surfaces:

- `trace(logdir)`: context manager around arbitrary device work.
- `StepProfiler`: step-windowed capture for the training loop — starts at
  `start_step`, captures `num_steps` steps, then stops and writes a
  `PROFILE_DONE` marker; the Tensorboard CR can point at the same logdir
  (tensorboard-plugin-profile reads the plugins/profile subdir).

The captured dir is the artifact; callers register it in the metadata
store for lineage like any pipeline output (SURVEY.md §5.1 "artifact =
trace dir registered in the metadata store").
"""

from __future__ import annotations

import contextlib
import os
from typing import Any, Callable

import numpy as np


@contextlib.contextmanager
def trace(logdir: str):
    """jax.profiler.trace with the dir created up front; yields the dir."""
    import jax

    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


class StepProfiler:
    """Capture a [start_step, start_step + num_steps) window of the train
    loop. `maybe_stop` takes a sync thunk because on the tunneled TPU
    platform dispatch returns before the device finishes — the caller must
    fetch a scalar to fence the trace (see .claude/skills/verify gotchas)."""

    def __init__(self, logdir: str, start_step: int = 2, num_steps: int = 3):
        if num_steps < 1:
            raise ValueError("profile_num_steps must be >= 1")
        self.logdir = logdir
        self.start_step = start_step
        self.end_step = start_step + num_steps
        self.active = False
        self.done = False

    def maybe_start(self, step: int) -> None:
        if self.done or self.active or step < self.start_step:
            return
        import jax

        os.makedirs(self.logdir, exist_ok=True)
        jax.profiler.start_trace(self.logdir)
        self.active = True

    def maybe_stop(self, step: int,
                   sync: Callable[[], Any] | None = None) -> None:
        if not self.active or step + 1 < self.end_step:
            return
        import jax

        if sync is not None:
            sync()  # fence: device work for the window must have retired
        jax.profiler.stop_trace()
        self.active = False
        self.done = True
        with open(os.path.join(self.logdir, "PROFILE_DONE"), "w") as f:
            f.write(f"steps {self.start_step}..{self.end_step - 1}\n")

    def close(self) -> None:
        """Stop a still-open window (loop ended early)."""
        if self.active:
            import jax

            jax.profiler.stop_trace()
            self.active = False


# -- serving-side decode-step attribution ------------------------------------
#
# The 8B roofline gap (ROADMAP #2): plain decode measured ~30 ms/step
# against a 9.2 ms weight-read floor, with nothing attributing the other
# ~21 ms. serving_decode_breakdown() closes the attribution hole: it
# drives the live engine's OWN compiled decode programs (plus two probe
# programs) and splits one decode step's wall time into the five buckets
# a serving step is made of. Differential timing, not trace parsing —
# the buckets come from executing program VARIANTS that differ by
# exactly one stage, so no profiler-proto tooling is needed at runtime;
# a jax.profiler trace of the full step is captured alongside as the
# registered artifact when trace_dir is given.


def _median_time(run, iters: int):
    import time

    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        run()
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def serving_decode_breakdown(engine, *, steps: int | None = None,
                             fill_len: int | None = None, iters: int = 5,
                             trace_dir: str | None = None,
                             hbm_gbps: float | None = None) -> dict:
    """Attribute one batched decode step of a (warmed, idle) LLMEngine.

    Returns a machine-readable dict whose `buckets_ms` splits a decode
    step into:

      weight_read          — measured: a jitted reduction that reads every
                             non-embed weight byte once and nothing else
                             (the HBM floor decode cannot beat);
      attention_kv_update  — the rest of the sampling-stripped forward:
                             attention over the KV span, cache update,
                             norms/activations (nosample-variant time
                             minus the weight read); sub-attributed by
                             two probe programs (ISSUE 15):
                             `attn_kernel` — the selected decode-
                             attention impl (xla einsum or the Pallas
                             flash-decode kernel) once per layer over
                             the live span at S_v=1, and `attn_dequant`
                             — reading + dequantizing the same int8
                             span and nothing else (0.0 on unquantized
                             caches; both None when the cache isn't a
                             single-program slab or is mesh-sharded).
                             Probes, not a partition: the bucket also
                             carries cache writes + MLP — but the
                             xla-vs-flash A/B delta lands in
                             attn_kernel while every other bucket
                             stays put, which is what makes the
                             serving_kernels record explainable;
      sampling_penalties   — full program minus the sampling-stripped
                             variant (_decode(sample=False));
      dispatch_rtt         — a trivial-program host->device->host round
                             trip, amortized per step over the chunk;
      host_fetch_replay    — the engine's live perf counters (fetch +
                             Python replay wall per step), None until the
                             engine has served decode traffic.

    The engine's slot state is junk during the run and reset after
    (exactly like warmup) — call only while idle. `fill_len` positions
    the synthetic slots mid-generation so the attention span is
    realistic; `hbm_gbps` adds the analytic weight-read floor next to
    the measured one."""
    import jax
    import jax.numpy as jnp

    n_slots = engine.n_slots
    if steps is None:
        steps = 1
        while steps * 2 <= engine.decode_chunk:
            steps *= 2
    # every (untimed + timed) run's KV writes must fit max_len so no
    # state reset is needed INSIDE a timed window (a reset is host
    # transfers — RTTs — that would pollute the chunk timing). Small
    # caches clamp steps, then iters, rather than silently profiling a
    # degenerate everything-clamped-at-max_len program state.
    def rows_needed(s, it):
        return (2 * it + 4) * s + 2
    while steps > 1 and rows_needed(steps, iters) > engine.max_len:
        steps //= 2
    while iters > 1 and rows_needed(steps, iters) > engine.max_len:
        iters -= 1
    if rows_needed(steps, iters) > engine.max_len:
        raise ValueError(
            f"max_len {engine.max_len} cannot hold one profiled chunk "
            f"(steps={steps}, iters={iters})")
    if fill_len is None:
        fill_len = max(1, min(engine.max_len // 2,
                              engine.max_len - rows_needed(steps, iters)))
    span = engine._pick_span(min(fill_len + steps, engine.max_len))

    def reset_state():
        engine.lengths = engine._put(
            np.full((n_slots,), fill_len, np.int32))
        engine.last_tokens = engine._put(np.ones((n_slots,), np.int32))
        engine.samp = engine._put(engine._samp_reset())

    active = engine._put(np.ones((n_slots,), bool))

    def run_decode(fn):
        def go():
            (engine.cache, engine.lengths, engine.last_tokens,
             engine.samp, engine.rng_key, out) = fn(
                engine.params, engine.cache, engine.lengths,
                engine.last_tokens, engine.samp, engine.rng_key, active,
                *engine._extra())
            float(np.asarray(out).flat[0])   # value fetch = the only
            # reliable sync on the tunneled platform (see StepProfiler)
        return go

    fn_full = engine._decode_fn(steps, span)
    # the sampling-stripped variant comes from the ENGINE (LLMEngine
    # jits its _decode with sample=False; the stage-sharded engine
    # returns its pipelined driver twin) so the differential stays
    # apples-to-apples per engine kind
    fn_nosample = engine._decode_nosample_fn(steps, span)

    # pure weight read: reduce every non-embed leaf to one scalar — reads
    # each byte exactly once, FLOPs are negligible, so its wall time IS
    # the achievable weight-read time of this chip (embed is excluded
    # because decode gathers a handful of its rows, never the table).
    # Stage-sharded engines hold params as a LIST of per-stage slabs —
    # strip each slab's embed the same way.
    params = engine.params
    if isinstance(params, dict):
        read_trees = [{k: v for k, v in params.items() if k != "embed"}]
    elif isinstance(params, list):
        # stage-sharded engine: one slab per stage, each on ITS OWN
        # device group — one jitted read per slab (a single program
        # spanning device groups is rejected), dispatched together so
        # per-stage reads overlap exactly like the pipeline's
        read_trees = [{k: v for k, v in slab.items() if k != "embed"}
                      for slab in params]
    else:
        read_trees = [params]
    read_bytes = int(sum(l.nbytes for t in read_trees
                         for l in jax.tree.leaves(t)))

    @jax.jit
    def read_all(p):
        tot = jnp.zeros((), jnp.float32)
        for leaf in jax.tree.leaves(p):
            tot = tot + jnp.sum(leaf).astype(jnp.float32)
        return tot

    def run_read():
        outs = [read_all(t) for t in read_trees]   # dispatch all first
        for o in outs:
            float(np.asarray(o))

    # trivial round trip: dispatch + scalar fetch of a one-add program —
    # the per-dispatch host<->device overhead every chunk pays once
    tiny = engine._put(np.zeros((), np.float32))
    tiny_fn = jax.jit(lambda x: x + 1.0)

    def run_rtt():
        float(np.asarray(tiny_fn(tiny)))

    # one untimed call per program: compiles (nosample/read/rtt are not
    # in the warmup menu) and faults pages before the timed iterations.
    # State is reset ONCE up front; fill_len left enough KV headroom for
    # every run's writes, so no host transfer lands inside a timed window
    reset_state()
    for warm in (run_decode(fn_full), run_decode(fn_nosample), run_read,
                 run_rtt):
        warm()

    t_rtt = _median_time(run_rtt, iters)
    if hasattr(engine, "pipeline_perf"):
        engine.pipeline_perf(reset=True)   # bracket the timed window
    t_full = _median_time(run_decode(fn_full), iters)
    # pipeline_bubble bucket (ISSUE 14 satellite): per-stage idle wall
    # per decode step, from the stage-sharded engine's own per-stage
    # timestamps (None for single-program engines, and None when the
    # engine runs with stage_timing off — the schedule-derived fraction
    # still rides the `pipeline` sub-record either way)
    pipe_bubble_ms = None
    pipe_snap = None
    if hasattr(engine, "pipeline_perf"):
        pipe_snap = engine.pipeline_perf(reset=True)
        if pipe_snap["steps"] and pipe_snap["bubble_frac"] is not None:
            n_st = pipe_snap["stages"]
            idle = (n_st * pipe_snap["window_s"]
                    - sum(pipe_snap["stage_busy_s"]))
            pipe_bubble_ms = round(
                max(idle, 0.0) / (n_st * pipe_snap["steps"]) * 1e3, 4)
    t_nosample = _median_time(run_decode(fn_nosample), iters)
    t_read = max(_median_time(run_read, iters) - t_rtt, 0.0)

    # kv_handoff bucket (ISSUE 13 satellite): the cost of moving one
    # radix block of finished prefill KV between engines — raw extract
    # (the banker's slice program) + zero-copy insert through the same
    # KVHandoff interface the disaggregated coordinator uses — so the
    # handoff's price sits NEXT TO weight-read/attention/sampling in the
    # committed breakdown instead of folding into dispatch-RTT. None on
    # engines without a prefix cache (no blocks to move), and on paged
    # engines — paged banking is refcount bookkeeping on pool blocks
    # (serving/paged.py _bank_prefix_blocks), there is no slice-out
    # handoff program to time.
    kv_handoff_ms = None
    if getattr(engine, "prefix_cache_enabled", False) \
            and engine.kvcache is not None \
            and getattr(engine, "_bank_uses_raw_extract", True):
        from kubeflow_tpu.kvcache import RadixKVCache
        from kubeflow_tpu.serving.disagg import KVHandoff

        bt = engine.prefix_block_tokens
        scratch = RadixKVCache(bt, 4)
        handoff = KVHandoff(lambda: scratch)
        probe_tokens = list(range(1, bt + 1))

        def run_handoff():
            parts = engine._extract_raw_fn(bt)(engine.cache, 0)
            payload = engine._payload_slice(parts, 0, bt)
            scratch.clear()   # nothing pins the scratch between runs
            handoff.send(probe_tokens, [payload])
            float(np.asarray(jax.tree.leaves(parts)[0]).flat[0])
            # ^ value-fetch sync

        run_handoff()   # compile + fault pages, untimed
        kv_handoff_ms = round(
            max(_median_time(run_handoff, iters) - t_rtt, 0.0) * 1e3, 4)

    # attn_kernel / attn_dequant sub-attribution (ISSUE 15 satellite):
    # the attention+KV bucket is a differential (nosample forward minus
    # weight read) — it cannot say what the ATTENTION itself costs vs
    # the int8 dequant riding it, which is exactly the split an
    # xla-vs-flash A/B needs to be explainable per bucket.
    attn_kernel_ms = None
    attn_dequant_ms = None
    prefill_attn_ms = None
    kv_gather_ms = None
    cfg = getattr(engine, "cfg", None)
    cache_obj = getattr(engine, "cache", None)
    if (cfg is not None and getattr(engine, "mesh", None) is None
            and isinstance(cache_obj, dict) and "k" in cache_obj):
        import jax.numpy as jnp

        from kubeflow_tpu.models import llama as _llama

        quantized = "k_s" in cache_obj
        # paged engines (serving/paged.py) keep pool blocks, not slot
        # rows: the probes read KV through the slot block tables — the
        # same indirection the decode program pays
        paged = "tbl" in cache_obj
        bt_blk = int(cache_obj["k"].shape[2]) if paged else 0
        nb = min(span // bt_blk, int(cache_obj["tbl"].shape[1])) \
            if paged else 0
        n_layers = int(cache_obj["k"].shape[0])
        q_probe = jax.random.normal(
            jax.random.key(7),
            (n_slots, 1, cfg.n_heads, cfg.head_dim)).astype(cfg.dtype)

        def _layer_span(cache, name, li):
            rows_all = jax.lax.dynamic_index_in_dim(
                cache[name], li, axis=0, keepdims=False)
            if paged:
                return rows_all   # whole pool layer; the table slices
            return jax.lax.slice_in_dim(rows_all, 0, span, axis=1)

        @jax.jit
        def attn_probe(cache, lengths):
            positions = lengths[:, None]   # S_v=1: one decode step
            tbl_b = cache["tbl"][:, :nb] if paged else None

            def body(acc, li):
                out = _llama.decode_attention(
                    cfg, q_probe,
                    _layer_span(cache, "k", li),
                    _layer_span(cache, "v", li),
                    _layer_span(cache, "k_s", li) if quantized else None,
                    _layer_span(cache, "v_s", li) if quantized else None,
                    positions, tables=tbl_b)
                return acc + jnp.sum(out.astype(jnp.float32)), None

            acc, _ = jax.lax.scan(body, jnp.float32(0.0),
                                  jnp.arange(n_layers))
            return acc

        def run_attn():
            float(np.asarray(attn_probe(engine.cache, engine.lengths)))

        run_attn()   # compile + fault pages, untimed
        attn_kernel_ms = round(
            max(_median_time(run_attn, iters) - t_rtt, 0.0) * 1e3, 4)

        # prefill_attn probe (ISSUE 20 satellite): one continuation
        # CHUNK of the selected prefill-attention impl (xla masked mha
        # or the Pallas flash-prefill kernel) per layer against the
        # live span — the TTFT-side twin of attn_kernel, so the
        # serving_prefill_kernels A/B delta has a bucket to land in.
        # Paged-aware: the probe reads KV through the slot block
        # tables, exactly like the chunked-prefill program.
        span_p = nb * bt_blk if paged else span
        pchunk = max(1, min(32, span_p))
        q_off = span_p - pchunk
        qp_probe = jax.random.normal(
            jax.random.key(11),
            (n_slots, pchunk, cfg.n_heads, cfg.head_dim)).astype(cfg.dtype)

        @jax.jit
        def prefill_probe(cache):
            tbl_b = cache["tbl"][:, :nb] if paged else None

            def body(acc, li):
                out = _llama.prefill_attention(
                    cfg, qp_probe,
                    _layer_span(cache, "k", li),
                    _layer_span(cache, "v", li),
                    _layer_span(cache, "k_s", li) if quantized else None,
                    _layer_span(cache, "v_s", li) if quantized else None,
                    q_offset=q_off, tables=tbl_b)
                return acc + jnp.sum(out.astype(jnp.float32)), None

            acc, _ = jax.lax.scan(body, jnp.float32(0.0),
                                  jnp.arange(n_layers))
            return acc

        def run_prefill_attn():
            float(np.asarray(prefill_probe(engine.cache)))

        run_prefill_attn()   # compile + fault pages, untimed
        prefill_attn_ms = round(
            max(_median_time(run_prefill_attn, iters) - t_rtt, 0.0)
            * 1e3, 4)

        def _gathered_span(cache, name, li):
            """The slot×span KV volume through the block tables (the
            paged read path): [slots, nb*bt, ...]."""
            pool = jax.lax.dynamic_index_in_dim(
                cache[name], li, axis=0, keepdims=False)
            g = jnp.take(pool, cache["tbl"][:, :nb], axis=0)
            return g.reshape((g.shape[0], nb * bt_blk) + g.shape[3:])

        if quantized:
            @jax.jit
            def dequant_probe(cache):
                def body(acc, li):
                    read = _gathered_span if paged else _layer_span
                    k = _llama.dequantize_kv(
                        read(cache, "k", li),
                        read(cache, "k_s", li), cfg.dtype)
                    v = _llama.dequantize_kv(
                        read(cache, "v", li),
                        read(cache, "v_s", li), cfg.dtype)
                    return acc + (jnp.sum(k.astype(jnp.float32))
                                  + jnp.sum(v.astype(jnp.float32))), None

                acc, _ = jax.lax.scan(body, jnp.float32(0.0),
                                      jnp.arange(n_layers))
                return acc

            def run_dequant():
                float(np.asarray(dequant_probe(engine.cache)))

            run_dequant()   # compile + fault pages, untimed
            attn_dequant_ms = round(
                max(_median_time(run_dequant, iters) - t_rtt, 0.0) * 1e3,
                4)
        else:
            attn_dequant_ms = 0.0   # nothing to dequantize, by definition

        if paged:
            # kv_gather (ISSUE 19 satellite): what the block-table
            # INDIRECTION itself costs — the same slot×span KV volume
            # read once through the tables (jnp.take over the block
            # axis) and once as a contiguous block range. The
            # difference is the tax paged residency puts on every
            # decode step's KV read; None on slab engines, where reads
            # are contiguous by construction.
            vol = min(n_slots * nb, int(cache_obj["k"].shape[1]))

            @jax.jit
            def gather_read(cache):
                def body(acc, li):
                    gk = _gathered_span(cache, "k", li)
                    gv = _gathered_span(cache, "v", li)
                    return acc + (jnp.sum(gk.astype(jnp.float32))
                                  + jnp.sum(gv.astype(jnp.float32))), None

                acc, _ = jax.lax.scan(body, jnp.float32(0.0),
                                      jnp.arange(n_layers))
                return acc

            @jax.jit
            def contig_read(cache):
                def body(acc, li):
                    kl = jax.lax.dynamic_index_in_dim(
                        cache["k"], li, axis=0, keepdims=False)
                    vl = jax.lax.dynamic_index_in_dim(
                        cache["v"], li, axis=0, keepdims=False)
                    ck = jax.lax.slice_in_dim(kl, 0, vol, axis=0)
                    cv = jax.lax.slice_in_dim(vl, 0, vol, axis=0)
                    return acc + (jnp.sum(ck.astype(jnp.float32))
                                  + jnp.sum(cv.astype(jnp.float32))), None

                acc, _ = jax.lax.scan(body, jnp.float32(0.0),
                                      jnp.arange(n_layers))
                return acc

            def run_gather():
                float(np.asarray(gather_read(engine.cache)))

            def run_contig():
                float(np.asarray(contig_read(engine.cache)))

            run_gather(); run_contig()   # compile, untimed
            kv_gather_ms = round(
                max(_median_time(run_gather, iters)
                    - _median_time(run_contig, iters), 0.0) * 1e3, 4)

    per_step = 1e3 / steps
    dev_full_ms = max(t_full - t_rtt, 0.0) * per_step
    dev_nosample_ms = max(t_nosample - t_rtt, 0.0) * per_step
    weight_read_ms = t_read * 1e3
    sampling_ms = max(dev_full_ms - dev_nosample_ms, 0.0)
    attn_kv_ms = max(dev_nosample_ms - weight_read_ms, 0.0)

    perf = engine.perf_counters()
    host_ms = None
    dispatch_host_ms = None
    if perf.get("decode_steps"):
        host_ms = round(perf["fetch_replay_s"] * 1e3
                        / perf["decode_steps"], 4)
        dispatch_host_ms = round(perf["dispatch_s"] * 1e3
                                 / perf["decode_steps"], 4)

    out = {
        "steps": steps, "span": span, "n_slots": n_slots,
        "fill_len": fill_len, "iters": iters,
        "chunk_wall_ms": round(t_full * 1e3, 4),
        "device_step_ms": round(dev_full_ms, 4),
        "dispatch_rtt_ms": round(t_rtt * 1e3, 4),
        "weight_read_bytes": read_bytes,
        "weight_read_gbps": round(read_bytes / max(t_read, 1e-9) / 1e9, 1),
        "buckets_ms": {
            "weight_read": round(weight_read_ms, 4),
            "attention_kv_update": round(attn_kv_ms, 4),
            # probe-based sub-attribution of attention_kv_update (the
            # xla-vs-flash A/B lever vs the int8 read+convert tax); not
            # part of the bucket partition
            "attn_kernel": attn_kernel_ms,
            "attn_dequant": attn_dequant_ms,
            # one continuation chunk of the selected prefill-attention
            # impl per layer over the live span (per CHUNK, not per
            # decode step — it rides prefill cadence); None when the
            # cache isn't a single-program slab/pool
            "prefill_attn": prefill_attn_ms,
            "sampling_penalties": round(sampling_ms, 4),
            "dispatch_rtt_per_step": round(t_rtt * per_step, 4),
            "host_fetch_replay_per_step": host_ms,
            # per BLOCK handed off, not per step: the handoff rides
            # prefill completion, so its cadence is per-request
            "kv_handoff": kv_handoff_ms,
            # block-table indirection tax on the decode-span KV read
            # (gather through slot tables minus contiguous read of the
            # same volume); None on slab engines, whose reads are
            # contiguous by construction
            "kv_gather": kv_gather_ms,
            # per-stage idle wall per decode step (stage-sharded
            # engines with stage_timing armed; None elsewhere)
            "pipeline_bubble": pipe_bubble_ms,
        },
        # live engine counters for the host-side buckets (per-chunk wall
        # the host spent dispatching vs fetching+replaying, amortized)
        "host_dispatch_per_step_ms": dispatch_host_ms,
        "perf_counters": perf,
    }
    if pipe_snap is not None:
        out["pipeline"] = pipe_snap
    if hbm_gbps:
        floor_ms = read_bytes / (hbm_gbps * 1e9) * 1e3
        out["weight_read_floor_ms"] = round(floor_ms, 4)
        out["weight_read_frac_of_peak"] = round(
            floor_ms / max(weight_read_ms, 1e-9), 4)
    if trace_dir:
        # the trace artifact: one full chunk under jax.profiler (the
        # breakdown above is what bench records; the trace is for humans
        # in tensorboard-plugin-profile, registered like any other dir)
        try:
            reset_state()
            with trace(trace_dir):
                run_decode(fn_full)()
            with open(os.path.join(trace_dir, "PROFILE_DONE"), "w") as f:
                f.write(f"decode chunk steps={steps} span={span}\n")
            out["trace_dir"] = trace_dir
        except Exception as e:   # profiling must never kill the bench
            out["trace_error"] = f"{type(e).__name__}: {e}"

    # leave the engine exactly as warmup does: slot state reset, host
    # mirrors zeroed (the junk cache rows are dead — the next prefill
    # into a slot rewrites them). The pipeline counters reset too: the
    # nosample/trace runs above fired record_step after the committed
    # snapshot, and profiler junk must not leak into the next live
    # metrics()["pipeline"] read.
    if hasattr(engine, "pipeline_perf"):
        engine.pipeline_perf(reset=True)
    engine.lengths = engine._put(np.zeros((n_slots,), np.int32))
    engine.last_tokens = engine._put(np.zeros((n_slots,), np.int32))
    engine.samp = engine._put(engine._samp_reset())
    engine._host_lengths[:] = 0
    engine._pending = None
    engine._inflight[:] = 0
    engine._active_host = None
    engine._active_dev = None
    return out
