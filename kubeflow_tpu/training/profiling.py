"""Profiling/tracing hooks — the platform's TensorBoard-profiler analog
(SURVEY.md §5.1: the reference delegates workload profiling to TF/torch
profilers surfaced through the tensorboard-controller; here `jax.profiler`
is first-class and the trace windows are part of the trainer config).

Two surfaces:

- `trace(logdir)`: context manager around arbitrary device work.
- `StepProfiler`: step-windowed capture for the training loop — starts at
  `start_step`, captures `num_steps` steps, then stops and writes a
  `PROFILE_DONE` marker; the Tensorboard CR can point at the same logdir
  (tensorboard-plugin-profile reads the plugins/profile subdir).

The captured dir is the artifact; callers register it in the metadata
store for lineage like any pipeline output (SURVEY.md §5.1 "artifact =
trace dir registered in the metadata store").
"""

from __future__ import annotations

import contextlib
import os
from typing import Any, Callable


@contextlib.contextmanager
def trace(logdir: str):
    """jax.profiler.trace with the dir created up front; yields the dir."""
    import jax

    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


class StepProfiler:
    """Capture a [start_step, start_step + num_steps) window of the train
    loop. `maybe_stop` takes a sync thunk because on the tunneled TPU
    platform dispatch returns before the device finishes — the caller must
    fetch a scalar to fence the trace (see .claude/skills/verify gotchas)."""

    def __init__(self, logdir: str, start_step: int = 2, num_steps: int = 3):
        if num_steps < 1:
            raise ValueError("profile_num_steps must be >= 1")
        self.logdir = logdir
        self.start_step = start_step
        self.end_step = start_step + num_steps
        self.active = False
        self.done = False

    def maybe_start(self, step: int) -> None:
        if self.done or self.active or step < self.start_step:
            return
        import jax

        os.makedirs(self.logdir, exist_ok=True)
        jax.profiler.start_trace(self.logdir)
        self.active = True

    def maybe_stop(self, step: int,
                   sync: Callable[[], Any] | None = None) -> None:
        if not self.active or step + 1 < self.end_step:
            return
        import jax

        if sync is not None:
            sync()  # fence: device work for the window must have retired
        jax.profiler.stop_trace()
        self.active = False
        self.done = True
        with open(os.path.join(self.logdir, "PROFILE_DONE"), "w") as f:
            f.write(f"steps {self.start_step}..{self.end_step - 1}\n")

    def close(self) -> None:
        """Stop a still-open window (loop ended early)."""
        if self.active:
            import jax

            jax.profiler.stop_trace()
            self.active = False
