"""The JAXJob trainer — the in-framework replacement for the reference's L7
user containers (torch DDP loops launched by PyTorchJob, SURVEY.md §3.1).

Where the reference injects MASTER_ADDR/WORLD_SIZE env vars and lets torch
build NCCL rings, this trainer receives a Mesh and expresses all parallelism
as shardings on one jitted train step; XLA inserts the collectives. One code
path covers 1 chip -> v5e-16 -> multi-slice: only the MeshConfig changes.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Iterator

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from kubeflow_tpu.models import registry
from kubeflow_tpu.parallel import (
    MeshConfig,
    active_mesh,
    make_mesh,
    logical_to_spec,
    tree_logical_to_sharding,
)
from kubeflow_tpu.training.data import DatasetConfig
from kubeflow_tpu.training.metrics_writer import MetricsWriter


@dataclasses.dataclass
class OptimizerConfig:
    name: str = "adamw"
    learning_rate: float = 3e-4
    warmup_steps: int = 100
    total_steps: int = 1000
    weight_decay: float = 0.01
    grad_clip: float = 1.0
    b1: float = 0.9
    b2: float = 0.95
    schedule: str = "cosine"  # cosine | linear | constant
    # first-moment dtype: "bfloat16" halves mu's HBM residency AND its
    # read+write traffic each step (+1 MFU pt at the bench shape); the
    # second moment stays f32 (its dynamic range matters for the rsqrt)
    mu_dtype: str | None = None
    # parameter-efficient fine-tuning: only params whose tree path starts
    # with this "/"-joined prefix train (e.g. "lora" for llama_lora);
    # everything else is frozen with optax.set_to_zero, so optimizer
    # moments exist ONLY for the trainable leaves — the memory contract
    # that lets an 8B LoRA fine-tune fit where full Adam state would not
    trainable_prefix: str | None = None


@dataclasses.dataclass
class TrainerConfig:
    model: str = "mnist_cnn"
    model_overrides: dict[str, Any] = dataclasses.field(default_factory=dict)
    batch_size: int = 8
    optimizer: OptimizerConfig = dataclasses.field(default_factory=OptimizerConfig)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    sharding_rules: dict[str, Any] = dataclasses.field(default_factory=dict)
    dataset: DatasetConfig = dataclasses.field(default_factory=DatasetConfig)
    seed: int = 0
    log_every: int = 10
    checkpoint_dir: str | None = None
    checkpoint_every: int = 200
    keep_checkpoints: int = 3
    # step-windowed jax.profiler capture (SURVEY.md §5.1); None disables
    profile_dir: str | None = None
    profile_start_step: int = 2
    profile_num_steps: int = 3


def _path_keys(path) -> tuple[str, ...]:
    """Normalize a jax tree path (DictKey/GetAttrKey/SequenceKey entries) to
    plain strings — trainable_prefix matching and the optimizer-state
    suffix-sharding fallback MUST normalize identically, so there is
    exactly one implementation."""
    return tuple(str(getattr(p, "key", getattr(p, "name",
                             getattr(p, "idx", p)))) for p in path)


def make_optimizer(cfg: OptimizerConfig) -> optax.GradientTransformation:
    if cfg.schedule == "cosine":
        sched = optax.warmup_cosine_decay_schedule(
            0.0, cfg.learning_rate, cfg.warmup_steps,
            max(cfg.total_steps, cfg.warmup_steps + 1))
    elif cfg.schedule == "linear":
        sched = optax.linear_schedule(cfg.learning_rate, 0.0, cfg.total_steps)
    else:
        sched = cfg.learning_rate
    mu_dtype = jnp.dtype(cfg.mu_dtype) if cfg.mu_dtype else None
    opt = {
        "adamw": lambda: optax.adamw(sched, b1=cfg.b1, b2=cfg.b2,
                                     weight_decay=cfg.weight_decay,
                                     mu_dtype=mu_dtype),
        "adam": lambda: optax.adam(sched, b1=cfg.b1, b2=cfg.b2,
                                   mu_dtype=mu_dtype),
        # sgd's momentum trace is its mu analog (accumulator_dtype)
        "sgd": lambda: optax.sgd(sched, momentum=0.9,
                                 accumulator_dtype=mu_dtype),
    }[cfg.name]()
    if cfg.trainable_prefix:
        prefix = tuple(cfg.trainable_prefix.split("/"))

        def labels(params):
            def lab(path, _):
                keys = _path_keys(path)
                return ("train" if keys[:len(prefix)] == prefix
                        else "freeze")
            return jax.tree_util.tree_map_with_path(lab, params)

        opt = optax.multi_transform(
            {"train": opt, "freeze": optax.set_to_zero()}, labels)
    if cfg.grad_clip:
        opt = optax.chain(optax.clip_by_global_norm(cfg.grad_clip), opt)
    return opt


class Trainer:
    """Builds the sharded train step for a registered model on a mesh."""

    def __init__(self, config: TrainerConfig, *, mesh: Mesh | None = None,
                 devices=None, metrics: MetricsWriter | None = None):
        self.config = config
        self.mesh = mesh if mesh is not None else make_mesh(config.mesh,
                                                            devices=devices)
        self.model = registry.get(config.model)
        self.model_cfg = self.model.config_cls(**config.model_overrides)
        self.optimizer = make_optimizer(config.optimizer)
        self.metrics = metrics or MetricsWriter()
        self.rules = config.sharding_rules

        logical = self.model.logical_axes(self.model_cfg)
        self.param_sharding = tree_logical_to_sharding(logical, self.mesh,
                                                       self.rules)
        self.batch_spec = logical_to_spec(("batch",), self.rules)
        self.batch_sharding = NamedSharding(self.mesh, self.batch_spec)
        # rank>=2 batch leaves ([B, S, ...] tokens/masks) additionally shard
        # dim 1 over the sequence axis (dropped at size 1 — a no-op off the
        # long-context path)
        self.batch_seq_spec = logical_to_spec(("batch", "seq"), self.rules)
        self.batch_seq_sharding = NamedSharding(self.mesh, self.batch_seq_spec)
        self.repl = NamedSharding(self.mesh, PartitionSpec())

        self._jit_init = None
        self._jit_step = None
        self._step_stats: dict[str, float] = {}

    # -- state ---------------------------------------------------------------

    def init_state(self) -> dict[str, Any]:
        """Initialize params+opt_state directly sharded on the mesh (no full
        replica ever materializes on one host — essential at 8B scale)."""
        if self._jit_init is None:
            def _init(rng):
                params = self.model.init(rng, self.model_cfg)
                opt_state = self.optimizer.init(params)
                return {"params": params, "opt_state": opt_state,
                        "step": jnp.zeros((), jnp.int32)}

            abstract = jax.eval_shape(_init, jax.random.key(self.config.seed))
            out_sh = self._state_sharding(abstract)
            self._jit_init = jax.jit(_init, out_shardings=out_sh)
        return self._jit_init(jax.random.key(self.config.seed))

    def _state_sharding(self, abstract_state):
        """Param shardings for params; optimizer momenta follow their params
        *structurally* (optax.tree_map_params — shape matching would confuse
        transposed same-shape weights like wq/wo); non-param leaves replicate.
        Wrapped optimizers optax can't traverse (multi_transform for
        trainable_prefix freezing) fall back to exact path-SUFFIX matching:
        a momentum leaf's trailing dict path IS its param's path (mu/nu
        mirror the params tree), so the match is as exact as the structural
        one — a same-shape transposed weight still can't confuse it."""
        try:
            opt_sh = optax.tree_map_params(
                self.optimizer,
                lambda _, sh: sh,
                abstract_state["opt_state"],
                self.param_sharding,
                transform_non_params=lambda _: self.repl,
            )
        except (ValueError, TypeError):
            # the ONLY known-untraversable optimizer is the multi_transform
            # wrapper trainable_prefix builds; any other failure here is a
            # real sharding-spec bug that must not hide behind the fallback
            if not self.config.optimizer.trainable_prefix:
                raise
            opt_sh = self._suffix_path_sharding(abstract_state)
        return {"params": self.param_sharding, "opt_state": opt_sh,
                "step": self.repl}

    def _suffix_path_sharding(self, abstract_state):
        norm = _path_keys
        flat_sh = {norm(p): sh for p, sh in
                   jax.tree_util.tree_flatten_with_path(
                       self.param_sharding,
                       is_leaf=lambda x: isinstance(x, NamedSharding))[0]}
        flat_shape = {norm(p): leaf.shape for p, leaf in
                      jax.tree_util.tree_flatten_with_path(
                          abstract_state["params"])[0]}

        def assign(path, leaf):
            keys = norm(path)
            for i in range(len(keys)):  # longest suffix first
                suf = keys[i:]
                if suf in flat_sh and flat_shape[suf] == leaf.shape:
                    return flat_sh[suf]
            return self.repl

        return jax.tree_util.tree_map_with_path(
            assign, abstract_state["opt_state"])

    def abstract_state(self) -> dict[str, Any]:
        """Sharding-annotated ShapeDtypeStructs of the train state — the
        checkpoint-restore target (no device memory touched)."""
        def _init(rng):
            params = self.model.init(rng, self.model_cfg)
            opt_state = self.optimizer.init(params)
            return {"params": params, "opt_state": opt_state,
                    "step": jnp.zeros((), jnp.int32)}

        abstract = jax.eval_shape(_init, jax.random.key(self.config.seed))
        shardings = self._state_sharding(abstract)
        return jax.tree.map(
            lambda l, sh: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=sh),
            abstract, shardings)

    # -- step ----------------------------------------------------------------

    def _build_step(self, example_batch):
        loss_fn = self.model.loss_fn
        model_cfg = self.model_cfg
        optimizer = self.optimizer

        def train_step(state, batch):
            def compute(params):
                return loss_fn(params, batch, model_cfg)

            (loss, metrics), grads = jax.value_and_grad(compute, has_aux=True)(
                state["params"])
            updates, new_opt = optimizer.update(grads, state["opt_state"],
                                                state["params"])
            new_params = optax.apply_updates(state["params"], updates)
            metrics = dict(metrics)
            metrics["grad_norm"] = optax.global_norm(grads)
            new_state = {"params": new_params, "opt_state": new_opt,
                         "step": state["step"] + 1}
            return new_state, metrics

        # state keeps the sharding it was initialized with (in_shardings=None
        # = "as given"); batch is forced onto the data (+sequence) axes.
        batch_sh = jax.tree.map(self._leaf_sharding, example_batch)
        jitted = self._jitted = jax.jit(
            train_step,
            in_shardings=(None, batch_sh),
            donate_argnums=(0,),
        )

        def step(state, batch):
            # ambient mesh for shard_map islands (ring/Ulysses attention,
            # MoE all-to-all) traced inside the jitted step
            with active_mesh(self.mesh):
                return jitted(state, batch)

        return step

    def aot_lower(self, abstract_batch):
        """AOT-lower the sharded train step from ShapeDtypeStructs alone —
        no device memory is touched, so an 8B-scale layout can be proven on
        hosts that could never hold the weights (training/contract.py)."""
        self._build_step(abstract_batch)
        with active_mesh(self.mesh):
            return self._jitted.lower(self.abstract_state(), abstract_batch)

    def compiled_step(self, state, example_batch):
        if self._jit_step is None:
            self._jit_step = self._build_step(example_batch)
        return self._jit_step

    def _leaf_sharding(self, x) -> NamedSharding:
        return (self.batch_seq_sharding if getattr(x, "ndim", 0) >= 2
                else self.batch_sharding)

    def shard_batch(self, batch: dict[str, Any]) -> dict[str, Any]:
        """Host batch -> global device arrays.

        Single-process: a committing device_put. Multi-host (a JAXJob
        spanning processes via jax.distributed): each host feeds its OWN
        rows — config.batch_size stays the GLOBAL batch, the data iterator
        on every host yields batch_size / process_count examples, and the
        per-host blocks are assembled into one global array without any
        cross-host transfer (the v5e-16 multi-host feeding path, SURVEY.md
        §5.8)."""
        if jax.process_count() == 1:
            return jax.tree.map(
                lambda x: jax.device_put(x, self._leaf_sharding(x)), batch)
        import numpy as np

        # np (not jnp): committing the local batch to a device first would
        # add a redundant whole-batch transfer before the per-device slicing
        return jax.tree.map(
            lambda x: jax.make_array_from_process_local_data(
                self._leaf_sharding(x), np.asarray(x)), batch)

    # -- loop ----------------------------------------------------------------

    def train(self, data: Iterator[dict[str, Any]], num_steps: int,
              state: dict[str, Any] | None = None,
              step_callback: Callable[[int, dict], None] | None = None):
        state = state if state is not None else self.init_state()
        ckpt = None
        if self.config.checkpoint_dir:
            from kubeflow_tpu.training.checkpoint import CheckpointManager

            ckpt = CheckpointManager(
                self.config.checkpoint_dir,
                max_to_keep=self.config.keep_checkpoints,
                save_interval_steps=self.config.checkpoint_every)
        step_fn = None
        t_last = time.perf_counter()
        steps_since_log = 0
        first_interval = True  # includes jit compile; flagged, not averaged in
        start_step = int(state["step"])
        prof = None
        if self.config.profile_dir:
            from kubeflow_tpu.training.profiling import StepProfiler

            # window is relative to THIS run's first step: on resume the
            # compile happens again, and profile_start_step exists to skip it
            prof = StepProfiler(self.config.profile_dir,
                                start_step + self.config.profile_start_step,
                                self.config.profile_num_steps)
        pending = None
        for i in range(num_steps):
            batch = (pending if pending is not None
                     else self.shard_batch(next(data)))
            pending = None
            if step_fn is None:
                step_fn = self.compiled_step(state, batch)
            step = start_step + i + 1
            if prof is not None:
                prof.maybe_start(step)
            state, metrics = step_fn(state, batch)
            # one-batch device prefetch: the next host->device transfer is
            # enqueued while this step runs, hiding it behind compute
            # (device_put/make_array are async dispatches). A data-iterator
            # failure here must not lose THIS step's log + checkpoint —
            # stash it and re-raise after the step's bookkeeping runs.
            data_err: BaseException | None = None
            if i + 1 < num_steps:
                try:
                    pending = self.shard_batch(next(data))
                except BaseException as e:
                    data_err = e
            if prof is not None:
                # sync by fetching a scalar: on the tunneled TPU platform
                # block_until_ready returns early, a fetch does not
                prof.maybe_stop(step, sync=lambda: jax.device_get(metrics))
            steps_since_log += 1
            if step % self.config.log_every == 0 or i == num_steps - 1:
                metrics = jax.device_get(metrics)
                now = time.perf_counter()
                dt = (now - t_last) / steps_since_log
                t_last = now
                steps_since_log = 0
                scalars = {k: float(v) for k, v in metrics.items()}
                scalars["step_time_s"] = dt
                if first_interval:
                    scalars["includes_compile"] = 1.0
                    first_interval = False
                self.metrics.write(step, scalars)
                if step_callback:
                    step_callback(step, scalars)
            if ckpt is not None:
                # manager applies save_interval_steps; final step forced below
                ckpt.save(step, state)
            if data_err is not None:
                raise data_err
        if prof is not None:
            prof.close()
        if ckpt is not None:
            final = start_step + num_steps
            if ckpt.latest_step() != final:  # interval may have saved it already
                ckpt.save(final, state, force=True)
            ckpt.close()
        return state
