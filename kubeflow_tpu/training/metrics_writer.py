"""Structured training-metric stream.

The reference's Katib metrics collector scrapes stdout with regexes or parses
tfevents files (SURVEY.md §2.3 metrics collector). Here the trainer emits
structured JSONL — `{"step": N, "metrics": {...}, "ts": ...}` per line — and
the HPO collector (kubeflow_tpu.hpo.collector) reads it back. Stdout echo is
kept for humans and for reference-style regex scraping compatibility.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import IO, Any


class MetricsWriter:
    def __init__(self, path: str | None = None, echo: bool = True):
        self.path = path
        self.echo = echo
        self._fh: IO[str] | None = None
        if path:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._fh = open(path, "a", buffering=1)

    def write(self, step: int, metrics: dict[str, Any]) -> None:
        rec = {"step": step, "metrics": metrics, "ts": time.time()}
        line = json.dumps(rec)
        if self._fh:
            self._fh.write(line + "\n")
        if self.echo:
            pretty = " ".join(f"{k}={v:.5g}" if isinstance(v, float) else f"{k}={v}"
                              for k, v in metrics.items())
            print(f"[step {step}] {pretty}", file=sys.stdout, flush=True)

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None


def read_metrics(path: str) -> list[dict[str, Any]]:
    out = []
    if not os.path.exists(path):
        return out
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line:
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    return out
