from kubeflow_tpu.training.trainer import OptimizerConfig, Trainer, TrainerConfig
from kubeflow_tpu.training.metrics_writer import MetricsWriter, read_metrics
from kubeflow_tpu.training.checkpoint import CheckpointManager, restore_or_init
from kubeflow_tpu.training.loader import (NativeTokenLoader, PyTokenLoader,
                                          token_file_dataset, write_corpus)

__all__ = ["Trainer", "TrainerConfig", "OptimizerConfig", "MetricsWriter",
           "read_metrics", "CheckpointManager", "restore_or_init",
           "NativeTokenLoader", "PyTokenLoader", "token_file_dataset",
           "write_corpus"]
