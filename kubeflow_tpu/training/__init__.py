from kubeflow_tpu.training.trainer import OptimizerConfig, Trainer, TrainerConfig
from kubeflow_tpu.training.metrics_writer import MetricsWriter, read_metrics
from kubeflow_tpu.training.checkpoint import CheckpointManager, restore_or_init

__all__ = ["Trainer", "TrainerConfig", "OptimizerConfig", "MetricsWriter",
           "read_metrics", "CheckpointManager", "restore_or_init"]
