"""Contract proofs for the BASELINE flagship shape: Llama-3-8B on v5e-16.

BASELINE.json config #3 ("Llama-3-8B multi-host JAXJob on v5e-16") is the
north-star workload, but no 16-chip slice exists on a dev box. This module
proves the contract shape anyway, the TPU-native way:

  - AOT-lower the FULL training step (fwd+bwd+adamw) at the true 8B
    dimensions over a 16-device fsdp x tensor mesh from ShapeDtypeStructs —
    GSPMD partitions the program without a single parameter materializing.
  - Compile the lowered module and read XLA's buffer assignment
    (``compiled.memory_analysis()``) for per-device argument/temp/output
    bytes; assert the peak fits v5e HBM (16 GiB).
  - Independently account the sharded train-state bytes analytically from
    the NamedShardings (exact, backend-independent).

Reference anchor (SURVEY.md §6 config #3): the reference platform would run
this as an MPIJob launching Megatron containers; here the same contract is a
mesh shape + logical-axis rules on one jitted step.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import jax
import jax.numpy as jnp

from kubeflow_tpu.parallel import MeshConfig

V5E_HBM_BYTES = 16 * 1024**3  # per-chip HBM on TPU v5e


def llama3_8b_overrides(seq_len: int = 8192) -> dict[str, Any]:
    """The true Llama-3-8B dimensions as Trainer model_overrides
    (models/llama.py LlamaConfig.llama3_8b, made explicit so the proof can't
    silently drift from the contract shape)."""
    return dict(
        vocab_size=128256, d_model=4096, n_layers=32, n_heads=32,
        n_kv_heads=8, d_ff=14336, max_seq_len=seq_len, rope_theta=500000.0,
        # full remat is the config that fits: against the real v5e compiler
        # (topology AOT, fsdp8 x tp2, batch 8, seq 8192), remat="minimal"
        # OOMs at 17.91G of 15.75G HBM; "full" compiles, heap-simulator
        # peak 15.2G (memory_analysis().peak_memory_in_bytes)
        remat=True, remat_policy="full",
    )


def _leaf_device_bytes(leaf: jax.ShapeDtypeStruct) -> int:
    shard = leaf.sharding.shard_shape(leaf.shape)
    return math.prod(shard) * leaf.dtype.itemsize


def analytic_state_bytes_per_device(trainer) -> int:
    """Exact per-device train-state residency from the NamedShardings
    (params + adam moments + step), independent of any backend."""
    return sum(_leaf_device_bytes(l)
               for l in jax.tree.leaves(trainer.abstract_state()))


def aot_8b_report(n_devices: int = 16, batch: int | None = None,
                  seq_len: int | None = None, do_compile: bool = True,
                  n_layers: int | None = None,
                  topology: str | None = None,
                  mesh_cfg: MeshConfig | None = None,
                  model_overrides: dict[str, Any] | None = None
                  ) -> dict[str, Any]:
    """Lower (and optionally compile) the 8B train step on an
    fsdp x tensor=2 mesh over `n_devices`; return the memory evidence.

    Runs anywhere with `n_devices` JAX devices — the driver's virtual-CPU
    mesh included. `topology` (e.g. "v5e:4x4") instead targets the REAL TPU
    compiler via PJRT topology AOT: no chips needed, and the memory analysis
    is the actual v5e HBM budget, not a CPU-buffer-assignment proxy.
    `do_compile=False` stops after StableHLO lowering (fast; proves sharding
    propagation at the true dims without invoking the backend compiler).
    `mesh_cfg`/`model_overrides` repurpose the same compile-and-measure
    machinery for other layouts (e.g. the 4D pipeline compile proof in
    tests/test_contract_8b.py).
    """
    from kubeflow_tpu.training import Trainer, TrainerConfig, OptimizerConfig

    if topology is not None:
        from jax.experimental import topologies

        devices = list(topologies.get_topology_desc(topology).devices)
        n_devices = len(devices)
    else:
        devices = jax.devices()[:n_devices]
    if mesh_cfg is None:
        mesh_cfg = MeshConfig(fsdp=n_devices // 2, tensor=2)
    resolved = mesh_cfg.resolved(n_devices)
    if model_overrides is not None:
        overrides = dict(model_overrides)
        # derive defaults FROM the overrides so a custom layout can't get
        # the 8B seq length by accident
        if seq_len is None:
            seq_len = overrides.get("max_seq_len", 2048)
    else:
        seq_len = seq_len if seq_len is not None else 8192
        overrides = llama3_8b_overrides(seq_len)
    if n_layers is not None:  # reduced-depth variant for execution tests
        overrides["n_layers"] = n_layers
    if batch is None:
        # 1 example per data-parallel shard, times the microbatch need of a
        # stage axis (the pipeline splits the batch into `stage` microbatches)
        batch = max(1, resolved.data * resolved.fsdp) * max(1, resolved.stage)
    trainer = Trainer(
        TrainerConfig(
            model="llama", model_overrides=overrides, batch_size=batch,
            optimizer=OptimizerConfig(warmup_steps=10, total_steps=100),
            mesh=mesh_cfg),
        devices=devices)

    abstract_batch = {"tokens": jax.ShapeDtypeStruct(
        (batch, seq_len), jnp.int32, sharding=trainer.batch_seq_sharding)}
    lowered = trainer.aot_lower(abstract_batch)

    n_params = sum(math.prod(l.shape) for l in jax.tree.leaves(
        jax.eval_shape(lambda: trainer.model.init(
            jax.random.key(0), trainer.model_cfg))))
    if model_overrides is not None:
        label = (f"llama-custom(d{overrides.get('d_model')}"
                 f"xL{overrides.get('n_layers')})")
    else:
        label = "llama3-8b" if n_layers is None else f"llama3-8b/L{n_layers}"
    report: dict[str, Any] = {
        "model": label,
        "n_params": n_params,
        "n_devices": n_devices,
        "target": topology or str(devices[0].platform),
        "mesh": {k: v for k, v in
                 dataclasses.asdict(mesh_cfg.resolved(n_devices)).items()
                 if v > 1},
        "batch": batch,
        "seq_len": seq_len,
        "analytic_state_bytes_per_device": analytic_state_bytes_per_device(
            trainer),
        "lowered": True,
    }
    if do_compile:
        # the TPU compiler enforces its HBM budget here: an oversubscribed
        # layout fails compile() with RESOURCE_EXHAUSTED ("Used 17.91G of
        # 15.75G hbm" for remat=minimal), so reaching memory_analysis() at
        # all already proves the layout fits the target
        compiled = lowered.compile()
        report["compiled"] = True
        ma = compiled.memory_analysis()
        if ma is not None:
            report["xla"] = {
                "argument_size_in_bytes": ma.argument_size_in_bytes,
                "output_size_in_bytes": ma.output_size_in_bytes,
                "temp_size_in_bytes": ma.temp_size_in_bytes,
                "alias_size_in_bytes": ma.alias_size_in_bytes,
            }
            # the heap simulator's own peak (accounts donation/aliasing);
            # 0 on backends that don't model it — fall back to the upper
            # bound args + temps (outputs alias donated inputs)
            peak = getattr(ma, "peak_memory_in_bytes", 0) or (
                ma.argument_size_in_bytes + ma.temp_size_in_bytes)
            report["peak_bytes_per_device"] = int(peak)
            report["v5e_hbm_bytes"] = V5E_HBM_BYTES
            report["fits_v5e_hbm"] = bool(peak <= V5E_HBM_BYTES)
    return report


if __name__ == "__main__":
    import json
    import sys

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 16
    print(json.dumps(aot_8b_report(n_devices=n)))
