"""kubeflow_tpu.obs — the zero-dependency telemetry layer (ISSUE 17).

Three pieces, each usable alone:

- ``obs.trace``: request-scoped tracing. A trace id is minted at the
  router (``X-Trace-Id``) or at ``submit()`` and rides every hop —
  router relay → supervisor journal → engine admission → disagg roles /
  pp stages — as plain string plumbing (no context-vars magic, so
  thread handoffs can't silently drop it). Spans land in a bounded
  in-process ring buffer, exportable as JSONL.
- ``obs.metrics``: THE process-wide instrument set over the existing
  ``utils.metrics.Registry`` text exporter. Every serving-plane metric
  name is declared here (scripts/check_observability.py enforces it),
  and ``render_metrics()`` is the one scrape path both ``ModelServer``
  and the router serve at ``GET /metrics``.
- ``obs.slo``: sliding-window per-tenant TTFT/TPOT attainment and
  error-budget burn rate, computed online with the ``loadgen/slo.py``
  predicate — the live counterpart of the offline scenario summary.
"""

from kubeflow_tpu.obs.build import build_stamp
from kubeflow_tpu.obs.metrics import render_metrics
from kubeflow_tpu.obs.slo import SloBurnTracker
from kubeflow_tpu.obs.trace import (TRACER, Span, SpanSink, Tracer,
                                    new_trace_id)

__all__ = ["TRACER", "Span", "SpanSink", "Tracer", "new_trace_id",
           "render_metrics", "SloBurnTracker", "build_stamp"]
