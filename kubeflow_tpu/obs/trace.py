"""Request-scoped tracing: Trace/Span context, bounded ring buffer,
JSONL export (ISSUE 17 tentpole, piece 1).

Design constraints, in order:

1. ZERO hot-loop cost when sampled out. The sampling decision is a
   deterministic hash of the trace id, so every layer of one request —
   router, supervisor, engine, roles, stages — independently reaches
   the SAME keep/drop verdict without coordination, and a dropped
   trace costs one blake2b per span site, no allocation.
2. The hot decode loop NEVER creates per-token spans. Engines keep the
   timestamps they already track (submit/first-token/finish) and emit
   ONE retrospective span per request per phase via ``record_span``;
   ``StepAggregator`` carries the per-step counters (steps, tokens)
   that annotate the decode span. scripts/check_observability.py
   enforces this statically.
3. Spans are plain dict-shaped facts in a bounded deque — an exporter
   crash or an unscraped buffer can only ever cost old spans
   (``dropped`` counts them), never memory.

Span kinds, the taxonomy (docs/ARCHITECTURE.md "Observability"):
``http`` (router relay / server handler), ``supervise`` (journal
lifetime incl. crash-replay chain), ``admit``, ``queue``, ``prefill``,
``handoff``, ``decode``, ``stage`` (pp microbatch wave), ``restart``,
``replay``.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from collections import deque
from typing import Any

#: HTTP header carrying the trace id across the router → server hop.
TRACE_HEADER = "X-Trace-Id"

_SAMPLE_SALT = b"ktpu-trace-v1"


def new_trace_id() -> str:
    """128-bit random hex — mint once at the edge (router or submit)."""
    return os.urandom(16).hex()


def _new_span_id() -> str:
    return os.urandom(8).hex()


class Span:
    """One timed hop of one request. Mutable until ``end()``; appended
    to the sink at end-time so half-open spans never export."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "kind",
                 "start_s", "end_s", "attrs", "_sink")

    def __init__(self, trace_id: str, name: str, kind: str,
                 parent_id: str | None = None, start_s: float | None = None,
                 attrs: dict[str, Any] | None = None,
                 sink: "SpanSink | None" = None):
        self.trace_id = trace_id
        self.span_id = _new_span_id()
        self.parent_id = parent_id
        self.name = name
        self.kind = kind
        self.start_s = time.monotonic() if start_s is None else start_s
        self.end_s: float | None = None
        self.attrs: dict[str, Any] = dict(attrs or {})
        self._sink = sink

    def set(self, **attrs: Any) -> "Span":
        self.attrs.update(attrs)
        return self

    def end(self, end_s: float | None = None, **attrs: Any) -> "Span":
        if self.end_s is not None:   # idempotent: double-end exports once
            return self
        self.end_s = time.monotonic() if end_s is None else end_s
        if attrs:
            self.attrs.update(attrs)
        if self._sink is not None:
            self._sink.append(self)
        return self

    def duration_ms(self) -> float | None:
        if self.end_s is None:
            return None
        return round((self.end_s - self.start_s) * 1e3, 3)

    def to_json(self) -> dict[str, Any]:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id, "name": self.name,
                "kind": self.kind, "start_s": self.start_s,
                "end_s": self.end_s, "duration_ms": self.duration_ms(),
                "attrs": self.attrs}

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.end()


class _NoopSpan:
    """The sampled-out stand-in: absorbs set/end/ctx use for free."""

    __slots__ = ()
    trace_id = span_id = parent_id = None
    end_s = None

    def set(self, **attrs: Any) -> "_NoopSpan":
        return self

    def end(self, end_s: float | None = None, **attrs: Any) -> "_NoopSpan":
        return self

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


NOOP_SPAN = _NoopSpan()


class SpanSink:
    """Bounded in-process ring buffer of ended spans."""

    def __init__(self, capacity: int = 4096):
        self._lock = threading.Lock()
        self._buf: deque[Span] = deque(maxlen=max(1, int(capacity)))
        self.dropped = 0

    @property
    def capacity(self) -> int:
        return self._buf.maxlen or 0

    def append(self, span: Span) -> None:
        with self._lock:
            if len(self._buf) == self._buf.maxlen:
                self.dropped += 1
            self._buf.append(span)

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def spans(self, trace_id: str | None = None) -> list[Span]:
        with self._lock:
            snap = list(self._buf)
        if trace_id is None:
            return snap
        return [s for s in snap if s.trace_id == trace_id]

    def export_jsonl(self, path: str | None = None,
                     trace_id: str | None = None) -> str:
        """One span per line, oldest first; optionally also written to
        ``path`` (the operator's trace-dump surface)."""
        text = "\n".join(json.dumps(s.to_json(), sort_keys=True)
                         for s in self.spans(trace_id))
        if text:
            text += "\n"
        if path is not None:
            with open(path, "w") as f:
                f.write(text)
        return text

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self.dropped = 0


class Tracer:
    """Sampling + span minting over one sink. ``sample_rate`` in [0,1];
    the decision is a pure function of the trace id so every layer
    agrees without sharing state."""

    def __init__(self, sink: SpanSink | None = None,
                 sample_rate: float = 1.0):
        self.sink = sink if sink is not None else SpanSink()
        self.sample_rate = float(sample_rate)

    def set_sample_rate(self, rate: float) -> float:
        self.sample_rate = min(1.0, max(0.0, float(rate)))
        return self.sample_rate

    def sampled(self, trace_id: str | None) -> bool:
        if not trace_id or self.sample_rate <= 0.0:
            return False
        if self.sample_rate >= 1.0:
            return True
        h = hashlib.blake2b(_SAMPLE_SALT + trace_id.encode(),
                            digest_size=8).digest()
        return int.from_bytes(h, "big") < self.sample_rate * 2.0**64

    def span(self, name: str, kind: str, trace_id: str | None,
             parent_id: str | None = None, start_s: float | None = None,
             **attrs: Any) -> Span | _NoopSpan:
        """Open a live span (context-manager friendly); exported when
        ended. Sampled-out (or traceless) calls return the shared noop."""
        if not self.sampled(trace_id):
            return NOOP_SPAN
        return Span(trace_id, name, kind, parent_id=parent_id,
                    start_s=start_s, attrs=attrs, sink=self.sink)

    def record_span(self, name: str, kind: str, trace_id: str | None,
                    start_s: float, end_s: float,
                    parent_id: str | None = None, **attrs: Any) -> None:
        """Retrospective span from timestamps a layer already kept —
        the ONLY emission style allowed on engine hot paths (zero cost
        until the request finishes, nothing per token)."""
        if start_s is None or end_s is None or not self.sampled(trace_id):
            return
        Span(trace_id, name, kind, parent_id=parent_id, start_s=start_s,
             attrs=attrs, sink=self.sink).end(end_s=end_s)


class StepAggregator:
    """The hot-loop recorder: per-step counter bumps only (no spans, no
    allocation), reduced to attrs for the ONE decode span a request
    gets. Engines snapshot ``steps``/``tokens`` at first-token and at
    finish; the difference annotates the retrospective decode span."""

    __slots__ = ("steps", "tokens")

    def __init__(self):
        self.steps = 0
        self.tokens = 0

    def note_step(self, n_tokens: int, steps: int = 1) -> None:
        """Count one dispatch (or a fused chunk of ``steps``) delivering
        up to ``n_tokens`` across the batch."""
        self.steps += int(steps)
        self.tokens += int(n_tokens)

    def snapshot(self) -> tuple[int, int]:
        return self.steps, self.tokens

    @staticmethod
    def window(at_start: tuple[int, int],
               at_end: tuple[int, int]) -> dict[str, int]:
        return {"decode_steps": at_end[0] - at_start[0],
                "decode_tokens": at_end[1] - at_start[1]}


#: the process tracer every layer shares (tests may swap the sink).
TRACER = Tracer()
