"""Online SLO burn accounting (ISSUE 17 tentpole, piece 3): sliding-
window per-tenant TTFT/TPOT attainment and error-budget burn rate,
computed request-by-request with the SAME predicate the offline
scenario summary uses (``loadgen.slo.request_meets``) — so the live
/metrics /healthz numbers and a committed loadgen record can never
disagree on what "meets SLO" means.

Burn rate is the SRE definition: with an error budget ``budget`` (the
tolerated miss fraction, default 1%), ``burn = miss_rate / budget`` —
1.0 means the tenant consumes its budget exactly at the wall-clock
rate, >1 means the budget exhausts early. This is ROADMAP #5's
autoscaler input signal.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Any

from kubeflow_tpu.loadgen.slo import request_meets

#: aggregate pseudo-tenant key in summaries and gauge labels
AGGREGATE = "_aggregate"


class SloBurnTracker:
    """Bounded sliding-window attainment/burn per tenant.

    Tenant cardinality is LRU-capped (``max_tenants``, the engine's
    MAX_TENANTS precedent) and each window holds at most
    ``max_samples`` — an adversarial tenant flood degrades precision,
    never memory."""

    def __init__(self, ttft_slo_ms: float = 2000.0,
                 tpot_slo_ms: float = 200.0, window_s: float = 300.0,
                 budget: float = 0.01, max_tenants: int = 256,
                 max_samples: int = 4096):
        self.ttft_slo_ms = float(ttft_slo_ms)
        self.tpot_slo_ms = float(tpot_slo_ms)
        self.window_s = float(window_s)
        self.budget = max(1e-6, float(budget))
        self.max_tenants = max(1, int(max_tenants))
        self.max_samples = max(16, int(max_samples))
        self._lock = threading.Lock()
        #: tenant -> deque[(t_mono, met: bool, ttft_ms, tpot_ms)]
        self._win: OrderedDict[str, deque] = OrderedDict()

    def record(self, tenant: str | None, ttft_ms: float | None,
               tpot_ms: float | None, completed: bool = True,
               now: float | None = None) -> bool:
        """Score one finished request; returns whether it met SLO."""
        met = request_meets(ttft_ms, tpot_ms,
                            ttft_slo_ms=self.ttft_slo_ms,
                            tpot_slo_ms=self.tpot_slo_ms,
                            completed=completed)
        t = time.monotonic() if now is None else now
        key = tenant or "default"
        with self._lock:
            win = self._win.get(key)
            if win is None:
                win = deque(maxlen=self.max_samples)
                self._win[key] = win
                while len(self._win) > self.max_tenants:
                    self._win.popitem(last=False)   # LRU: oldest tenant
            else:
                self._win.move_to_end(key)
            win.append((t, met, ttft_ms, tpot_ms))
        return met

    def _prune(self, win: deque, now: float) -> None:
        cutoff = now - self.window_s
        while win and win[0][0] < cutoff:
            win.popleft()

    @staticmethod
    def _reduce(samples: list, budget: float) -> dict[str, Any]:
        n = len(samples)
        met = sum(1 for s in samples if s[1])
        attainment = round(met / n, 4) if n else None
        burn = (round((1.0 - met / n) / budget, 3) if n else None)
        ttfts = sorted(s[2] for s in samples if s[2] is not None)
        worst = round(ttfts[-1], 3) if ttfts else None
        return {"n": n, "met": met, "attainment": attainment,
                "burn_rate": burn, "worst_ttft_ms": worst}

    def summary(self, now: float | None = None) -> dict[str, Any]:
        """The /healthz ``slo`` section: per-tenant window stats plus
        the aggregate, under the window/SLO config that produced them."""
        t = time.monotonic() if now is None else now
        with self._lock:
            per: dict[str, list] = {}
            for tenant, win in self._win.items():
                self._prune(win, t)
                if win:
                    per[tenant] = list(win)
        all_samples = [s for ss in per.values() for s in ss]
        return {
            "window_s": self.window_s,
            "slo": {"ttft_ms": self.ttft_slo_ms,
                    "tpot_ms": self.tpot_slo_ms,
                    "error_budget": self.budget},
            "aggregate": self._reduce(all_samples, self.budget),
            "tenants": {tenant: self._reduce(ss, self.budget)
                        for tenant, ss in sorted(per.items())},
        }

    def publish(self, _owner: Any = None) -> None:
        """Scrape hook body: refresh the slo_* gauges from the live
        window (obs.metrics.add_scrape_hook(tracker, SloBurnTracker.
        publish) wires it)."""
        from kubeflow_tpu.obs import metrics as m

        s = self.summary()
        agg = s["aggregate"]
        if agg["attainment"] is not None:
            m.SLO_ATTAINMENT.set(agg["attainment"], tenant=AGGREGATE)
            m.SLO_BURN_RATE.set(agg["burn_rate"], tenant=AGGREGATE)
        for tenant, row in s["tenants"].items():
            if row["attainment"] is not None:
                m.SLO_ATTAINMENT.set(row["attainment"], tenant=tenant)
                m.SLO_BURN_RATE.set(row["burn_rate"], tenant=tenant)
