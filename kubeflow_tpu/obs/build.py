"""Build/runtime stamp for /healthz (ISSUE 17 satellite): the
kubeflow_tpu version plus the jax/jaxlib pair and the live device view,
so fleet tooling can detect restarts and version skew from one GET.

This is the bench runtime-stamp helper promoted into the package —
bench._runtime_stamp delegates here so a committed record and a live
/healthz can never disagree on what "the runtime" means."""

from __future__ import annotations

from typing import Any

from kubeflow_tpu.version import __version__

_STAMP: dict[str, Any] | None = None


def runtime_stamp() -> dict[str, Any]:
    """platform/device_kind/device_count/jax/jaxlib of THIS process.
    Touches the jax backend, so callers on latency paths should prefer
    the cached ``build_stamp()``."""
    import jax

    dev = jax.devices()[0]
    try:
        import jaxlib

        jaxlib_v = getattr(jaxlib, "__version__", None)
    except Exception:
        jaxlib_v = None
    return {
        "platform": str(dev.platform),
        "device_kind": str(dev.device_kind),
        "device_count": jax.device_count(),
        "jax": jax.__version__,
        "jaxlib": jaxlib_v or jax.__version__,
    }


def build_stamp() -> dict[str, Any]:
    """The /healthz ``build`` section: version skew surface. Computed
    once per process (the device view cannot change under a fixed
    backend) and never raises — a frontend must stay healthy even if
    the accelerator runtime is broken enough to fail a device query."""
    global _STAMP
    if _STAMP is None:
        stamp: dict[str, Any] = {"kubeflow_tpu": __version__}
        try:
            stamp.update(runtime_stamp())
        except Exception as e:   # jax missing/broken: version info only
            stamp["runtime_error"] = f"{type(e).__name__}: {e}"
        _STAMP = stamp
    return dict(_STAMP)
