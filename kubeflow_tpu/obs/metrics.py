"""THE serving-plane instrument set (ISSUE 17 tentpole, piece 2).

One process-wide registry — ``utils.metrics.REGISTRY``, the same object
the control plane's reconciler instruments live in — and every
serving-layer metric NAME declared in this module, nowhere else
(scripts/check_observability.py enforces it: ad-hoc
``registry.counter("...")`` calls outside the central modules are lint
findings). Engine, supervisor, router, radix cache, heartbeat and
scheduler import instruments from here; ``render_metrics()`` is the one
scrape path ``GET /metrics`` serves on ModelServer AND the router.

Naming convention (docs/ARCHITECTURE.md "Observability"):
``<plane>_<noun>_<unit|total>`` with the component/event split carried
by labels, not name proliferation — e.g. every lifecycle event of every
layer is ``serving_requests_total{component=,event=}``.

Pull-model gauges (queue depth, circuit state, SLO burn) come from
SCRAPE HOOKS: live objects register a callback that refreshes their
gauges just before each render. Hooks hold a weakref to their owner so
a closed-but-not-deregistered engine can never keep itself alive or
poison later scrapes.
"""

from __future__ import annotations

import threading
import weakref
from typing import Any, Callable

from kubeflow_tpu.utils.metrics import REGISTRY, Registry  # noqa: F401

# -- explicit latency buckets (seconds) ---------------------------------------
# TTFT spans queue+prefill: sub-10ms cache hits through multi-second
# cold chunked prefills. TPOT is per-token: sub-ms kernel steps through
# ~1s interpret-mode smoke steps. Queue-wait shares TTFT's shape but
# needs the sub-ms floor for idle-engine admissions.
TTFT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                5.0, 10.0, 30.0)
TPOT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                0.5, 1.0, 2.5)
QUEUE_WAIT_BUCKETS = (0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0,
                      5.0, 30.0)

# -- request lifecycle (every layer, one name) --------------------------------
REQUESTS = REGISTRY.counter(
    "serving_requests_total",
    "Request lifecycle events across serving layers",
    ["component", "event"])
TTFT_SECONDS = REGISTRY.histogram(
    "serving_ttft_seconds", "Submit to first token", ["component"],
    buckets=TTFT_BUCKETS)
TPOT_SECONDS = REGISTRY.histogram(
    "serving_tpot_seconds", "Per-token decode latency (per request)",
    ["component"], buckets=TPOT_BUCKETS)
QUEUE_WAIT_SECONDS = REGISTRY.histogram(
    "serving_queue_wait_seconds", "Submit to prefill dispatch",
    ["component"], buckets=QUEUE_WAIT_BUCKETS)
PHASE_SECONDS = REGISTRY.histogram(
    "serving_phase_seconds",
    "Per-request phase walls (prefill/handoff/decode)",
    ["component", "phase"], buckets=QUEUE_WAIT_BUCKETS)
INFLIGHT = REGISTRY.gauge(
    "serving_inflight", "Live requests per component", ["component"])

# -- HTTP frontends -----------------------------------------------------------
HTTP_REQUESTS = REGISTRY.counter(
    "serving_http_requests_total", "Frontend requests by model and verb",
    ["model", "verb"])
HTTP_LATENCY = REGISTRY.histogram(
    "serving_http_request_seconds", "Frontend request wall",
    ["model", "verb"])
MODEL_READY = REGISTRY.gauge(
    "serving_model_ready", "1 = model loaded and ready", ["model"])
MODEL_LOAD_SECONDS = REGISTRY.histogram(
    "serving_model_load_seconds", "Model load() wall", ["model"])

# -- router -------------------------------------------------------------------
#: closed=0, half_open=1, open=2 (ordered by escalation)
CIRCUIT_STATE_CODES = {"closed": 0, "half_open": 1, "open": 2}
CIRCUIT_STATE = REGISTRY.gauge(
    "router_circuit_state",
    "Per-backend breaker state (0=closed 1=half_open 2=open)",
    ["backend"])
CIRCUIT_TRANSITIONS = REGISTRY.counter(
    "router_circuit_transitions_total",
    "Breaker state entries by target state", ["backend", "to"])

# -- supervisor ---------------------------------------------------------------
SUPERVISOR_RESTARTS = REGISTRY.counter(
    "supervisor_restarts_total", "Engine restarts by detected cause",
    ["cause"])

# -- kv/prefix cache ----------------------------------------------------------
PREFIX_EVENTS = REGISTRY.counter(
    "kvcache_prefix_events_total",
    "Radix prefix-cache events (hit/miss/insert/evict)", ["event"])
KV_FREE_BLOCKS = REGISTRY.gauge(
    "kvcache_free_blocks",
    "Allocatable KV blocks currently free (paged: pool free list; "
    "slab: radix store headroom)", ["engine"])
KV_WATERMARK_FRAC = REGISTRY.gauge(
    "kvcache_watermark_frac",
    "Free fraction of allocatable KV capacity (the paged admission "
    "signal: 1.0 = empty, 0.0 = fully committed)", ["engine"])

# -- heartbeat ----------------------------------------------------------------
HEARTBEAT_EVENTS = REGISTRY.counter(
    "heartbeat_events_total", "Reporter sends by outcome "
    "(sent/failed/dropped)", ["event"])
HEARTBEAT_CONSECUTIVE_FAILURES = REGISTRY.gauge(
    "heartbeat_consecutive_failures",
    "Consecutive failed sends of the live reporter", [])
HEARTBEAT_REPORTER_DEAD = REGISTRY.gauge(
    "heartbeat_reporter_dead", "1 = reporter exhausted its retry budget",
    [])

# -- attention impls (scrape-hook fed) ----------------------------------------
ATTENTION_IMPL = REGISTRY.gauge(
    "serving_attention_impl_info",
    "Resolved attention impl per engine phase (info-style: one series "
    "per (engine, phase=prefill|decode, impl=xla|flash), value 1)",
    ["engine", "phase", "impl"])

# -- scheduler (scrape-hook fed) ----------------------------------------------
SCHED_QUEUED = REGISTRY.gauge(
    "scheduler_queued", "Requests waiting for admission", ["engine"])
SCHED_ACTIVE = REGISTRY.gauge(
    "scheduler_active", "Requests holding decode slots", ["engine"])
SCHED_SHED = REGISTRY.counter(
    "scheduler_shed_total", "Requests shed by degraded-mode policy",
    ["engine"])

# -- SLO burn (scrape-hook fed from SloBurnTracker) ---------------------------
SLO_ATTAINMENT = REGISTRY.gauge(
    "slo_attainment", "Windowed SLO attainment per tenant", ["tenant"])
SLO_BURN_RATE = REGISTRY.gauge(
    "slo_burn_rate",
    "Windowed error-budget burn multiplier per tenant (1.0 = burning "
    "exactly the budget)", ["tenant"])

# -- tracing self-observation -------------------------------------------------
TRACE_BUFFER_SPANS = REGISTRY.gauge(
    "trace_buffer_spans", "Spans currently held in the ring buffer", [])
TRACE_SPANS_DROPPED = REGISTRY.gauge(
    "trace_spans_dropped_total", "Spans evicted from the full ring "
    "buffer since last clear", [])

# -- scrape hooks -------------------------------------------------------------

_hooks_lock = threading.Lock()
_hooks: list[tuple[weakref.ref, Callable[[Any], None]]] = []


def add_scrape_hook(owner: Any, fn: Callable[[Any], None]) -> None:
    """Refresh-before-render callback: ``fn(owner)`` runs on every
    ``render_metrics()``. Held via weakref to ``owner`` — when the owner
    is collected the hook silently unregisters, so short-lived engines
    in tests cannot accumulate."""
    with _hooks_lock:
        _hooks.append((weakref.ref(owner), fn))


def remove_scrape_hooks(owner: Any) -> None:
    with _hooks_lock:
        _hooks[:] = [(r, f) for r, f in _hooks if r() is not owner]


def run_scrape_hooks() -> None:
    with _hooks_lock:
        live = [(r, f) for r, f in _hooks if r() is not None]
        _hooks[:] = live
        snapshot = list(live)
    for ref, fn in snapshot:
        owner = ref()
        if owner is None:
            continue
        try:
            fn(owner)
        except Exception:
            # a dying component must not take the scrape down with it
            pass


def render_metrics() -> str:
    """THE scrape path: refresh pull-model gauges, then render the one
    process registry as Prometheus text."""
    from kubeflow_tpu.obs.trace import TRACER

    run_scrape_hooks()
    TRACE_BUFFER_SPANS.set(len(TRACER.sink))
    TRACE_SPANS_DROPPED.set(TRACER.sink.dropped)
    return REGISTRY.render()
