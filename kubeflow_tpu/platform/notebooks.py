"""Notebook controller (SURVEY.md §2.1, ⊘ components/notebook-controller
`NotebookReconciler.Reconcile` + jupyter-web-app spawner semantics).

A Notebook materializes a long-running workspace pod. Upstream semantics
kept: the `kubeflow-resource-stopped` annotation scales the workspace to
zero without deleting the Notebook (the dashboard's stop button), removing
it brings the pod back; idle culling sets that annotation automatically
after `spec.idleTimeoutSeconds` of no activity (activity = the workspace
touching its `lastActivity` status, here updated on pod restarts and
via the API's touch endpoint).

    kind: Notebook
    spec:
      template: {backend: thread, target: notebook_workspace, ...}
      resources: {cpu: 1}
      idleTimeoutSeconds: 3600       # optional auto-cull
"""

from __future__ import annotations

import time
from typing import Any

from kubeflow_tpu.control.controller import Controller
from kubeflow_tpu.control.executor import worker_target
from kubeflow_tpu.control.store import AlreadyExistsError, new_resource

NOTEBOOK_KIND = "Notebook"
STOPPED_ANNOTATION = "kubeflow-resource-stopped"
NOTEBOOK_LABEL = "kubeflow-tpu/notebook-name"


@worker_target("notebook_workspace")
def _workspace(env, cancel):
    """Default workspace process: parks until culled/stopped (the stand-in
    for a jupyter server; real images would use backend: subprocess)."""
    cancel.wait()


class NotebookController(Controller):
    kind = NOTEBOOK_KIND
    owned_kinds = ("Pod",)
    resync_period = 1.0

    def reconcile(self, nb: dict[str, Any]) -> float | None:
        name = nb["metadata"]["name"]
        ns = nb["metadata"].get("namespace", "default")
        spec = nb.get("spec", {})
        stopped = STOPPED_ANNOTATION in nb["metadata"].get("annotations", {})
        pod_name = f"{name}-workspace-0"
        pod = self.store.try_get("Pod", pod_name, ns)

        # idle culling: no activity since the timeout -> set the stopped
        # annotation (exactly what upstream's culler does)
        idle = spec.get("idleTimeoutSeconds")
        if idle and not stopped:
            last = nb["status"].get("lastActivity",
                                    nb["metadata"].get("creationTimestamp", 0))
            if time.time() - last > idle:
                self.store.mutate(NOTEBOOK_KIND, name, lambda o: (
                    o["metadata"].setdefault("annotations", {}).update(
                        {STOPPED_ANNOTATION: "true"}),
                    o["status"].update(phase="Culled")), ns)
                return 0.0

        if stopped:
            if pod is not None:
                self.store.try_delete("Pod", pod_name, ns)
            if nb["status"].get("phase") not in ("Stopped", "Culled"):
                self.store.mutate(NOTEBOOK_KIND, name, lambda o: o["status"]
                                  .update(phase="Stopped"), ns)
            return None

        if pod is None:
            template = dict(spec.get("template") or
                            {"backend": "thread",
                             "target": "notebook_workspace"})
            template.setdefault("resources", spec.get("resources",
                                                      {"cpu": 1}))
            env = dict(template.get("env", {}))
            env["KTPU_NOTEBOOK_NAME"] = name
            template["env"] = env
            try:
                self.store.create(new_resource(
                    "Pod", pod_name, spec=template, namespace=ns,
                    labels={NOTEBOOK_LABEL: name}, owner=nb))
            except AlreadyExistsError:
                pass
            self.store.mutate(NOTEBOOK_KIND, name, lambda o: o["status"]
                              .update(phase="Starting",
                                      lastActivity=time.time()), ns)
            return 0.2

        phase = pod["status"].get("phase", "Pending")
        want = {"Running": "Ready", "Pending": "Starting",
                "Scheduled": "Starting"}.get(phase, phase)
        if nb["status"].get("phase") != want:
            self.store.mutate(NOTEBOOK_KIND, name, lambda o: o["status"]
                              .update(phase=want), ns)
        if idle:
            return min(float(idle) / 2.0, 5.0)
        return None


def touch(store, name: str, namespace: str = "default") -> None:
    """Record workspace activity (API layer calls this on user traffic) —
    resets the idle-culling clock and restarts a culled notebook."""
    def _update(o):
        o["status"]["lastActivity"] = time.time()
        o["metadata"].get("annotations", {}).pop(STOPPED_ANNOTATION, None)
    store.mutate(NOTEBOOK_KIND, name, _update, namespace)
