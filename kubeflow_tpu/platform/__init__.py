"""Multi-tenancy & platform glue — the kubeflow/kubeflow L2 components
(SURVEY.md §2.1) rebuilt over the TPU-native control plane: Profiles/KFAM,
PodDefault admission, notebooks, tensorboards, volumes, dashboard."""

from kubeflow_tpu.platform.dashboard import (  # noqa: F401
    dashboard,
    namespace_summary,
)
from kubeflow_tpu.platform.notebooks import (  # noqa: F401
    NOTEBOOK_KIND,
    NotebookController,
    touch,
)
from kubeflow_tpu.platform.poddefaults import (  # noqa: F401
    PODDEFAULT_KIND,
    apply_poddefaults_on_pod,
    install_poddefault_webhook,
)
from kubeflow_tpu.platform.profiles import (  # noqa: F401
    BINDING_KIND,
    PROFILE_KIND,
    ProfileController,
    bindings_for_user,
    can_access,
    ensure_binding,
    remove_binding,
    validate_profile,
)
from kubeflow_tpu.platform.tensorboards import (  # noqa: F401
    TENSORBOARD_KIND,
    TensorboardController,
    read_scalars,
)
from kubeflow_tpu.platform.volumes import (  # noqa: F401
    VIEWER_KIND,
    VOLUME_KIND,
    PVCViewerController,
    VolumeController,
)
