"""Tensorboard controller (SURVEY.md §2.1, ⊘ components/tensorboard-
controller `tensorboard_controller.go`): a Tensorboard CR points at a
training logdir and gets a scalar-serving endpoint.

The TPU-native twist: trainers write structured JSONL metrics
(training/metrics_writer.py) instead of tfevents, so "serving a logdir" is
parsing that stream — `read_scalars` is the data source the dashboard/API
exposes at /tensorboards/{name}/scalars, and the controller's job is
lifecycle/status (logdir exists -> Ready), not process babysitting.

    kind: Tensorboard
    spec: {logdir: /path/to/run}
"""

from __future__ import annotations

import json
import os
from typing import Any

from kubeflow_tpu.control.controller import Controller

TENSORBOARD_KIND = "Tensorboard"


def read_scalars(logdir: str, tag: str | None = None
                 ) -> dict[str, list[tuple[int, float]]]:
    """Parse JSONL metric streams under logdir into {tag: [(step, value)]}.
    Accepts both a directory of *.jsonl files and a single file path."""
    paths: list[str] = []
    if os.path.isdir(logdir):
        for fn in sorted(os.listdir(logdir)):
            if fn.endswith(".jsonl"):
                paths.append(os.path.join(logdir, fn))
    elif os.path.exists(logdir):
        paths.append(logdir)
    out: dict[str, list[tuple[int, float]]] = {}
    for path in paths:
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                step = int(rec.get("step", 0))
                for key, val in rec.items():
                    if key == "step" or not isinstance(val, (int, float)):
                        continue
                    if tag is not None and key != tag:
                        continue
                    out.setdefault(key, []).append((step, float(val)))
    for series in out.values():
        series.sort(key=lambda p: p[0])
    return out


class TensorboardController(Controller):
    kind = TENSORBOARD_KIND
    resync_period = 2.0

    def reconcile(self, tb: dict[str, Any]) -> float | None:
        name = tb["metadata"]["name"]
        ns = tb["metadata"].get("namespace", "default")
        logdir = tb.get("spec", {}).get("logdir")
        if not logdir:
            self.store.mutate(TENSORBOARD_KIND, name, lambda o: o["status"]
                              .update(phase="Invalid",
                                      message="spec.logdir is required"), ns)
            return None
        exists = os.path.exists(logdir)
        scalars = read_scalars(logdir) if exists else {}
        phase = "Ready" if exists else "WaitingForLogdir"
        tags = sorted(scalars)
        points = sum(len(v) for v in scalars.values())

        def write(o):
            o["status"].update(phase=phase, tags=tags, points=points)
        if (tb["status"].get("phase") != phase
                or tb["status"].get("points") != points):
            self.store.mutate(TENSORBOARD_KIND, name, write, ns)
        return 2.0 if not exists else 5.0
