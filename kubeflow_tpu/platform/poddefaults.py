"""PodDefault admission — the kubeflow admission-webhook analog (SURVEY.md
§2.1, ⊘ components/admission-webhook `mutatePods`/`applyPodDefaultsOnPod`).

A PodDefault declares env/labels/annotations to inject into pods whose
labels match its selector, namespace-scoped:

    kind: PodDefault
    spec:
      selector: {matchLabels: {team: vision}}
      env: {HF_HOME: /cache/hf}
      labels: {...}
      annotations: {...}

Where upstream runs a mutating webhook in the API-server admission chain,
here the injection point is the ResourceStore's mutating-hook chain — same
semantics (applied at create, before the executor ever sees the pod).
"""

from __future__ import annotations

from typing import Any

PODDEFAULT_KIND = "PodDefault"


def matches(selector: dict[str, Any], labels: dict[str, str]) -> bool:
    wanted = (selector or {}).get("matchLabels", {})
    return all(labels.get(k) == v for k, v in wanted.items())


def apply_poddefaults_on_pod(store, pod: dict[str, Any]) -> None:
    """The mutating hook: merge every matching PodDefault into the pod.
    Pod-level values win over injected defaults (same as upstream, which
    only adds what's absent)."""
    ns = pod["metadata"].get("namespace", "default")
    labels = pod["metadata"].get("labels", {})
    for pd in store.list(PODDEFAULT_KIND, ns):
        spec = pd.get("spec", {})
        if not matches(spec.get("selector"), labels):
            continue
        env = pod["spec"].setdefault("env", {})
        for k, v in spec.get("env", {}).items():
            env.setdefault(k, v)
        for k, v in spec.get("labels", {}).items():
            pod["metadata"]["labels"].setdefault(k, v)
        ann = pod["metadata"].setdefault("annotations", {})
        for k, v in spec.get("annotations", {}).items():
            ann.setdefault(k, v)
        ann.setdefault("kubeflow-tpu/poddefaults", "")
        applied = [a for a in ann["kubeflow-tpu/poddefaults"].split(",") if a]
        applied.append(pd["metadata"]["name"])
        ann["kubeflow-tpu/poddefaults"] = ",".join(applied)


def install_poddefault_webhook(store) -> None:
    store.add_mutating_hook("Pod", apply_poddefaults_on_pod)
