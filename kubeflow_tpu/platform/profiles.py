"""Profile controller + KFAM access management (SURVEY.md §2.1, ⊘
components/profile-controller `ProfileReconciler.Reconcile` and
components/access-management `CreateBinding`/`QueryClusterAdmin`).

A Profile is the multi-tenancy unit: it materializes a Namespace, a
ResourceQuota, and an owner AccessBinding. KFAM's contributor flow is the
AccessBinding CRUD + `can_access` query the dashboard/API layer consults.

    kind: Profile
    spec:
      owner: alice@example.com
      resourceQuota: {tpu: 8, cpu: 16}    # optional hard caps
"""

from __future__ import annotations

from typing import Any

from kubeflow_tpu.control.controller import Controller
from kubeflow_tpu.control.store import AlreadyExistsError, new_resource

PROFILE_KIND = "Profile"
BINDING_KIND = "AccessBinding"
ROLE_OWNER = "owner"
ROLE_CONTRIBUTOR = "contributor"


def validate_profile(profile: dict[str, Any]) -> list[str]:
    errs = []
    if not profile.get("spec", {}).get("owner"):
        errs.append("spec.owner is required")
    quota = profile.get("spec", {}).get("resourceQuota", {})
    for k, v in quota.items():
        if not isinstance(v, (int, float)) or v < 0:
            errs.append(f"resourceQuota.{k} must be a non-negative number")
    return errs


class ProfileController(Controller):
    kind = PROFILE_KIND
    owned_kinds = ()

    def reconcile(self, profile: dict[str, Any]) -> float | None:
        name = profile["metadata"]["name"]
        errs = validate_profile(profile)
        if errs:
            self.store.mutate(PROFILE_KIND, name, lambda o: o["status"].update(
                phase="Invalid", message="; ".join(errs)),
                profile["metadata"].get("namespace", "default"))
            return None

        # Profiles are cluster-scoped objects living in "default"; the
        # namespace they materialize carries the profile's name.
        if self.store.try_get("Namespace", name, "default") is None:
            try:
                self.store.create(new_resource(
                    "Namespace", name, spec={}, namespace="default",
                    owner=profile))
            except AlreadyExistsError:
                pass
        quota = profile["spec"].get("resourceQuota")
        if quota and self.store.try_get("ResourceQuota", name, name) is None:
            try:
                self.store.create(new_resource(
                    "ResourceQuota", name, spec={"hard": dict(quota)},
                    namespace=name, owner=profile))
            except AlreadyExistsError:
                pass
        ensure_binding(self.store, profile["spec"]["owner"], name, ROLE_OWNER,
                       owner=profile)
        if profile["status"].get("phase") != "Ready":
            self.store.mutate(
                PROFILE_KIND, name,
                lambda o: o["status"].update(phase="Ready"),
                profile["metadata"].get("namespace", "default"))
        return None


# -- KFAM (access management) -------------------------------------------------

def _binding_name(user: str, namespace: str) -> str:
    return f"{user.replace('@', '-').replace('.', '-')}-{namespace}"


def ensure_binding(store, user: str, namespace: str,
                   role: str = ROLE_CONTRIBUTOR, owner=None) -> dict[str, Any]:
    """CreateBinding analog: grant `user` access to a profile namespace.
    Bindings are stored in the profile's namespace, like upstream's
    RoleBindings."""
    name = _binding_name(user, namespace)
    existing = store.try_get(BINDING_KIND, name, namespace)
    if existing is not None:
        return existing
    try:
        return store.create(new_resource(
            BINDING_KIND, name,
            spec={"user": user, "role": role}, namespace=namespace,
            owner=owner))
    except AlreadyExistsError:
        return store.get(BINDING_KIND, name, namespace)


def remove_binding(store, user: str, namespace: str) -> bool:
    name = _binding_name(user, namespace)
    try:
        store.delete(BINDING_KIND, name, namespace)
        return True
    except Exception:
        return False


def bindings_for_user(store, user: str) -> list[dict[str, Any]]:
    """QueryClusterAdmin-style: every namespace binding a user holds."""
    return [b for b in store.list(BINDING_KIND, None)
            if b["spec"].get("user") == user]


def can_access(store, user: str, namespace: str,
               require_owner: bool = False) -> bool:
    b = store.try_get(BINDING_KIND, _binding_name(user, namespace), namespace)
    if b is None:
        return False
    return (not require_owner) or b["spec"].get("role") == ROLE_OWNER
