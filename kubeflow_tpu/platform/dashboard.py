"""Central-dashboard aggregation (SURVEY.md §2.1, ⊘ components/
centraldashboard): the namespace-scoped activity summary the dashboard
shell renders — counts + recent items for every resource family, filtered
by the caller's KFAM bindings.
"""

from __future__ import annotations

from typing import Any

from kubeflow_tpu.platform.profiles import bindings_for_user

_FAMILIES = {
    "jobs": "JAXJob",
    "experiments": "Experiment",
    "runs": "PipelineRun",
    "inferenceServices": "InferenceService",
    "notebooks": "Notebook",
    "tensorboards": "Tensorboard",
    "volumes": "Volume",
}


def _phase_of(obj: dict[str, Any]) -> str:
    status = obj.get("status", {})
    if "phase" in status:
        return str(status["phase"])
    conds = status.get("conditions") or []
    return str(conds[-1]["type"]) if conds else "Pending"


def namespace_summary(store, namespace: str) -> dict[str, Any]:
    out: dict[str, Any] = {"namespace": namespace}
    for family, kind in _FAMILIES.items():
        objs = store.list(kind, namespace)
        phases: dict[str, int] = {}
        for o in objs:
            p = _phase_of(o)
            phases[p] = phases.get(p, 0) + 1
        recent = sorted(objs, key=lambda o: o["metadata"]
                        .get("creationTimestamp", 0), reverse=True)[:5]
        out[family] = {
            "total": len(objs),
            "phases": phases,
            "recent": [{"name": o["metadata"]["name"],
                        "phase": _phase_of(o)} for o in recent],
        }
    return out


def dashboard(store, user: str | None = None) -> dict[str, Any]:
    """Whole-platform view: all namespaces (or just the user's, per KFAM)."""
    if user is not None:
        namespaces = sorted({b["metadata"]["namespace"]
                             for b in bindings_for_user(store, user)})
    else:
        namespaces = sorted({o["metadata"]["name"]
                             for o in store.list("Namespace", None)})
        if not namespaces:
            namespaces = ["default"]
    return {"user": user,
            "namespaces": [namespace_summary(store, ns)
                           for ns in namespaces]}
