"""Volumes + PVC viewer (SURVEY.md §2.1, ⊘ crud-web-apps/volumes and
components/pvcviewer-controller): a Volume is the PVC analog (a managed
directory under the cluster's data root with a size cap recorded in spec),
and a PVCViewer exposes a file listing of one volume — the filebrowser-pod
analog, served from status instead of a per-PVC pod.

    kind: Volume
    spec: {sizeGi: 10}

    kind: PVCViewer
    spec: {volume: my-vol}
"""

from __future__ import annotations

import os
from typing import Any

from kubeflow_tpu.control.controller import Controller

VOLUME_KIND = "Volume"
VIEWER_KIND = "PVCViewer"


def default_volumes_root() -> str:
    """The one place the volumes layout root is decided — shared by this
    controller and the serving storage-initializer's pvc:// fetcher, so the
    two halves of the contract can't disagree. KTPU_VOLUMES_ROOT overrides."""
    return os.environ.get("KTPU_VOLUMES_ROOT") or os.path.join(
        os.environ.get("TMPDIR", "/tmp"), "kubeflow-tpu-volumes")


def volume_path(root: str, ns: str, name: str) -> str:
    return os.path.join(root, ns, name)


class VolumeController(Controller):
    kind = VOLUME_KIND

    def __init__(self, cluster, data_root: str | None = None):
        super().__init__(cluster)
        self.data_root = data_root or default_volumes_root()

    def volume_path(self, ns: str, name: str) -> str:
        return volume_path(self.data_root, ns, name)

    def reconcile(self, vol: dict[str, Any]) -> float | None:
        name = vol["metadata"]["name"]
        ns = vol["metadata"].get("namespace", "default")
        path = self.volume_path(ns, name)
        os.makedirs(path, exist_ok=True)
        if vol["status"].get("phase") != "Bound":
            self.store.mutate(VOLUME_KIND, name, lambda o: o["status"].update(
                phase="Bound", path=path), ns)
        return None


class PVCViewerController(Controller):
    kind = VIEWER_KIND
    resync_period = 2.0

    def reconcile(self, viewer: dict[str, Any]) -> float | None:
        name = viewer["metadata"]["name"]
        ns = viewer["metadata"].get("namespace", "default")
        vol_name = viewer.get("spec", {}).get("volume")
        vol = self.store.try_get(VOLUME_KIND, vol_name, ns) if vol_name \
            else None
        if vol is None or vol["status"].get("phase") != "Bound":
            self.store.mutate(VIEWER_KIND, name, lambda o: o["status"].update(
                phase="WaitingForVolume"), ns)
            return 1.0
        root = vol["status"]["path"]
        files = []
        for dirpath, _dirnames, filenames in os.walk(root):
            rel = os.path.relpath(dirpath, root)
            for fn in sorted(filenames):
                p = os.path.join(dirpath, fn)
                files.append({
                    "path": fn if rel == "." else os.path.join(rel, fn),
                    "sizeBytes": os.path.getsize(p),
                })
        files.sort(key=lambda f: f["path"])

        def write(o):
            o["status"].update(phase="Ready", files=files)
        if viewer["status"].get("files") != files or \
                viewer["status"].get("phase") != "Ready":
            self.store.mutate(VIEWER_KIND, name, write, ns)
        return 2.0
