"""Framework-compat training job kinds — the unified training-operator's
per-framework controllers (SURVEY.md §2.2: TFJob/PyTorchJob/XGBoostJob/
MXJob/PaddleJob/MPIJob rows) rebuilt on the shared JAXJob reconcile engine.

Each controller differs from JAXJob ONLY in its `SetClusterSpec` analog —
the rendezvous environment it injects into pods — exactly how the reference
hosts every framework on one kubeflow/common JobController and specializes
per-kind env generation (⊘ training-operator `pkg/controller.v1/*/
*_controller.go SetClusterSpec`):

- TFJob       → `TF_CONFIG` JSON (cluster spec + task)      ⊘ genClusterSpec
- PyTorchJob  → `MASTER_ADDR`/`MASTER_PORT`/`WORLD_SIZE`/`RANK` (+ `PET_*`
                when elasticPolicy is set)
- XGBoostJob  → Rabit tracker env (`DMLC_TRACKER_URI` ...)
- MXJob       → PS root env (`DMLC_PS_ROOT_URI` ...)
- PaddleJob   → `PADDLE_TRAINER_ENDPOINTS`/`PADDLE_CURRENT_ENDPOINT` ...
- MPIJob      → hostfile ConfigMap + `OMPI_MCA_orte_default_hostfile` on
                the launcher                      ⊘ mpi-operator newConfigMap

Everything else — gang scheduling, expectations, RunPolicy (restart/backoff/
deadline/TTL), elastic resize, heartbeat failure detection, status
conditions — is inherited unchanged from JAXJobController.

Pods here are processes on one host, so every "service DNS name" becomes
127.0.0.1 with a deterministic per-rank port (the headless-Service stable
naming analog, SURVEY.md §5.8).
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any

from kubeflow_tpu.control.jobs import (JAXJobController, _effective_replicas,
                                       _replica_order)
from kubeflow_tpu.control.store import AlreadyExistsError, new_resource

TFJOB_KIND = "TFJob"
PYTORCHJOB_KIND = "PyTorchJob"
XGBOOSTJOB_KIND = "XGBoostJob"
MXJOB_KIND = "MXJob"
PADDLEJOB_KIND = "PaddleJob"
MPIJOB_KIND = "MPIJob"


class _FrameworkJobController(JAXJobController):
    """Shared helpers for per-rank host:port assignment."""

    singleton_roles = ("master",)

    def _host_port(self, job, rank: int) -> str:
        # coordinator port is the job's base; ranks get base+1+rank
        return f"127.0.0.1:{self._coordinator_port(job) + 1 + rank}"

    def _order(self, job) -> list[tuple[str, int]]:
        return _replica_order(job["spec"], _effective_replicas(job),
                              self.role_priority)


class TFJobController(_FrameworkJobController):
    """TFJob: injects TF_CONFIG per pod (⊘ tfjob_controller.go
    SetClusterSpec / genClusterSpec, SURVEY.md §3.2)."""

    kind = TFJOB_KIND
    roles = ("chief", "master", "ps", "worker", "evaluator")
    singleton_roles = ("chief", "master")
    role_priority = ("chief", "master")
    success_roles = ("chief", "master", "worker")

    def cluster_env(self, job, rtype, idx, rank, world):
        order = self._order(job)
        cluster: dict[str, list[str]] = {}
        for r, (t, _i) in enumerate(order):
            cluster.setdefault(t, []).append(self._host_port(job, r))
        tf_config = {
            "cluster": cluster,
            "task": {"type": rtype, "index": idx},
            "environment": "cloud",
        }
        return {"TF_CONFIG": json.dumps(tf_config, sort_keys=True)}


class PyTorchJobController(_FrameworkJobController):
    """PyTorchJob: MASTER_ADDR/PORT + WORLD_SIZE/RANK for the c10d TCPStore
    rendezvous (⊘ pytorchjob_controller.go SetClusterSpec, SURVEY.md §3.1);
    PET_* torchelastic env when elasticPolicy is present."""

    kind = PYTORCHJOB_KIND
    roles = ("master", "worker")
    success_roles = ("master", "worker")

    def cluster_env(self, job, rtype, idx, rank, world):
        addr, port = self._host_port(job, 0).split(":")
        env = {
            "MASTER_ADDR": addr,
            "MASTER_PORT": port,
            "WORLD_SIZE": str(world),
            "RANK": str(rank),
            "LOCAL_RANK": "0",
        }
        elastic = job["spec"].get("elasticPolicy")
        if elastic:
            env.update({
                "PET_RDZV_BACKEND": elastic.get("rdzvBackend", "c10d"),
                "PET_RDZV_ENDPOINT": f"{addr}:{port}",
                "PET_MIN_SIZE": str(elastic.get("minReplicas", 1)),
                "PET_MAX_SIZE": str(elastic.get("maxReplicas", world)),
                "PET_NNODES": str(world),
                "PET_NPROC_PER_NODE": "1",
            })
        return env


class XGBoostJobController(_FrameworkJobController):
    """XGBoostJob: Rabit tracker env rooted at master-0
    (⊘ xgboostjob_controller.go SetPodEnv)."""

    kind = XGBOOSTJOB_KIND
    roles = ("master", "worker")

    def cluster_env(self, job, rtype, idx, rank, world):
        addr, port = self._host_port(job, 0).split(":")
        workers = _effective_replicas(job).get("worker", 0)
        return {
            "MASTER_ADDR": addr,
            "MASTER_PORT": port,
            "WORLD_SIZE": str(world),
            "RANK": str(rank),
            "DMLC_TRACKER_URI": addr,
            "DMLC_TRACKER_PORT": port,
            "DMLC_NUM_WORKER": str(workers),
            "DMLC_NUM_SERVER": "0",
            "DMLC_TASK_ID": str(idx),
            "DMLC_ROLE": "master" if rtype == "master" else "worker",
        }


class MXJobController(_FrameworkJobController):
    """MXJob: DMLC parameter-server root env rooted at the scheduler
    (⊘ mxjob_controller.go SetClusterSpec)."""

    kind = MXJOB_KIND
    roles = ("scheduler", "server", "worker")
    singleton_roles = ("scheduler",)
    role_priority = ("scheduler",)
    success_roles = ("worker",)

    def cluster_env(self, job, rtype, idx, rank, world):
        addr, port = self._host_port(job, 0).split(":")
        eff = _effective_replicas(job)
        return {
            "DMLC_PS_ROOT_URI": addr,
            "DMLC_PS_ROOT_PORT": port,
            "DMLC_NUM_SERVER": str(eff.get("server", 0)),
            "DMLC_NUM_WORKER": str(eff.get("worker", 0)),
            "DMLC_ROLE": rtype,
            "DMLC_TASK_ID": str(idx),
        }


class PaddleJobController(_FrameworkJobController):
    """PaddleJob: trainer endpoint list + this pod's endpoint
    (⊘ paddlejob_controller.go SetClusterSpec)."""

    kind = PADDLEJOB_KIND
    roles = ("master", "ps", "worker")
    success_roles = ("master", "worker")

    def cluster_env(self, job, rtype, idx, rank, world):
        order = self._order(job)
        worker_hosts = [self._host_port(job, r)
                        for r, (t, _i) in enumerate(order) if t == "worker"]
        env = {
            "PADDLE_TRAINERS_NUM": str(len(worker_hosts)),
            "PADDLE_TRAINER_ENDPOINTS": ",".join(worker_hosts),
            "PADDLE_CURRENT_ENDPOINT": self._host_port(job, rank),
        }
        if rtype == "worker":
            # trainer id indexes PADDLE_TRAINER_ENDPOINTS: fleet expects
            # trainer_endpoints[trainer_id] == current_endpoint, so it is the
            # worker index, NOT the global rank (master/ps are not trainers)
            env["PADDLE_TRAINER_ID"] = str(idx)
            env["PADDLE_CURRENT_ENDPOINT"] = worker_hosts[idx]
        return env


class MPIJobController(_FrameworkJobController):
    """MPIJob: launcher + workers; generates the hostfile ConfigMap the
    launcher's mpirun consumes (⊘ mpi_job_controller.go newConfigMap,
    SURVEY.md §2.2 MPIJob row). The hostfile is also materialized to a real
    path so an actual `mpirun --hostfile` can read it."""

    kind = MPIJOB_KIND
    roles = ("launcher", "worker")
    singleton_roles = ("launcher",)
    role_priority = ("launcher",)
    success_roles = ("launcher",)

    def _hostfile(self, job) -> tuple[str, str]:
        name = job["metadata"]["name"]
        ns = job["metadata"].get("namespace", "default")
        workers = _effective_replicas(job).get("worker", 0)
        slots = (job["spec"]["replicaSpecs"].get("worker", {})
                 .get("template", {}).get("resources", {}).get("cpu", 1))
        content = "".join(f"{name}-worker-{i} slots={slots}\n"
                          for i in range(workers))
        path = os.path.join(tempfile.gettempdir(),
                            f"ktpu-{ns}-{name}-hostfile")
        return content, path

    def cluster_env(self, job, rtype, idx, rank, world):
        if rtype != "launcher":
            return {"OMPI_COMM_WORLD_RANK": str(rank - 1)}
        content, path = self._hostfile(job)
        name = job["metadata"]["name"]
        ns = job["metadata"].get("namespace", "default")
        cm = new_resource("ConfigMap", f"{name}-config",
                          spec={"data": {"hostfile": content}},
                          namespace=ns, owner=job)
        try:
            self.store.create(cm)
        except AlreadyExistsError:
            self.store.mutate(
                "ConfigMap", f"{name}-config",
                lambda o: o["spec"]["data"].update(hostfile=content), ns)
        with open(path, "w") as f:
            f.write(content)
        return {"OMPI_MCA_orte_default_hostfile": path}


TRAINING_CONTROLLERS: tuple[type[JAXJobController], ...] = (
    TFJobController, PyTorchJobController, XGBoostJobController,
    MXJobController, PaddleJobController, MPIJobController)

FRAMEWORK_KINDS: tuple[str, ...] = tuple(
    c.kind for c in TRAINING_CONTROLLERS)

# every training job kind, JAXJob first (the canonical list — cli.py and
# hpo/trial.py must agree on what exists). RLJob rides the same engine but
# its controller lives in kubeflow_tpu/rl/job.py, which imports THIS
# package — so the kind constant is defined HERE (rl/job.py imports it;
# that direction is cycle-free) and the class is resolved lazily by
# _all_controllers() (add/validate time, never import time).
RL_JOB_KIND = "RLJob"
ALL_JOB_KINDS: tuple[str, ...] = ((JAXJobController.kind,)
                                  + FRAMEWORK_KINDS + (RL_JOB_KIND,))


def _all_controllers() -> tuple[type[JAXJobController], ...]:
    from kubeflow_tpu.rl.job import RLJobController

    return TRAINING_CONTROLLERS + (RLJobController,)


def add_training_controllers(cluster) -> None:
    """Register every framework job kind on a Cluster — the unified
    training-operator manager analog (one manager, all reconcilers,
    ⊘ cmd/training-operator.v1/main.go)."""
    for ctrl in _all_controllers():
        cluster.add(ctrl)


def job_validators() -> dict[str, Any]:
    """kind → validator map for the admission layer (api/specs.py)."""
    return {c.kind: c.validate for c in _all_controllers()}
