"""JAXJob controller — the training-operator + kubeflow/common reconcile
engine (SURVEY.md §2.2, §3.1) rebuilt around JAX processes.

Spec shape (PyTorchJob-compatible skeleton):

    kind: JAXJob
    spec:
      runPolicy:
        backoffLimit: 3              # total restarts before Failed
        activeDeadlineSeconds: 600
        ttlSecondsAfterFinished: 5
        cleanPodPolicy: Running      # Running | All | None
        schedulingPolicy: {minAvailable: N}   # gang size, default Σreplicas
      successPolicy: Worker0         # Worker0 | AllWorkers
      replicaSpecs:
        worker:
          replicas: 4
          restartPolicy: OnFailure   # Never | OnFailure | Always | ExitCode
          template:
            backend: thread | subprocess
            target: <registered fn> | argv: [...] | command: "python -c ..."
            env: {...}
            resources: {tpu: 1, cpu: 1}

Where the reference injects MASTER_ADDR/WORLD_SIZE/RANK for torch's TCPStore
rendezvous, this controller injects KTPU_COORDINATOR_ADDRESS /
KTPU_NUM_PROCESSES / KTPU_PROCESS_ID for `jax.distributed.initialize`
(SURVEY.md §5.8) — consumed via kubeflow_tpu.runtime.bootstrap.

ExitCode restart policy follows the reference's convention: exit codes >=128
(SIGKILL'd, preempted) are retryable; 1–127 are permanent failures.
"""

from __future__ import annotations

import time
from typing import Any

from kubeflow_tpu.control.conditions import (JobConditionType, is_finished,
                                             set_condition)
from kubeflow_tpu.control.controller import Controller
from kubeflow_tpu.control.scheduler import GROUP_LABEL
from kubeflow_tpu.control.store import (AlreadyExistsError, NotFoundError,
                                        new_resource)

JOB_KIND = "JAXJob"
JOB_NAME_LABEL = "kubeflow-tpu/job-name"
REPLICA_TYPE_LABEL = "kubeflow-tpu/replica-type"
REPLICA_INDEX_LABEL = "kubeflow-tpu/replica-index"

_BASE_PORT = 47000


def validate_job(job: dict[str, Any]) -> list[str]:
    """Table-driven spec validation (admission-webhook analog)."""
    errs = []
    spec = job.get("spec", {})
    replicas = spec.get("replicaSpecs", {})
    if not replicas:
        errs.append("spec.replicaSpecs must define at least one replica type")
    for rtype, rspec in replicas.items():
        n = rspec.get("replicas", 1)
        if not isinstance(n, int) or n < 1:
            errs.append(f"replicaSpecs.{rtype}.replicas must be >= 1")
        rp = rspec.get("restartPolicy", "Never")
        if rp not in ("Never", "OnFailure", "Always", "ExitCode"):
            errs.append(f"replicaSpecs.{rtype}.restartPolicy invalid: {rp}")
        if "template" not in rspec:
            errs.append(f"replicaSpecs.{rtype}.template is required")
    run = spec.get("runPolicy", {})
    if run.get("backoffLimit", 0) < 0:
        errs.append("runPolicy.backoffLimit must be >= 0")
    sp = spec.get("successPolicy", "Worker0")
    if sp not in ("Worker0", "AllWorkers"):
        errs.append(f"successPolicy invalid: {sp}")
    return errs


def _replica_order(spec: dict[str, Any]) -> list[tuple[str, int]]:
    """Deterministic global process ranking: replica types sorted (master
    first if present), then index — the genClusterSpec ordering analog."""
    order: list[tuple[str, int]] = []
    rtypes = sorted(spec.get("replicaSpecs", {}),
                    key=lambda t: (t != "master", t))
    for rtype in rtypes:
        for i in range(spec["replicaSpecs"][rtype].get("replicas", 1)):
            order.append((rtype, i))
    return order


class JAXJobController(Controller):
    kind = JOB_KIND
    owned_kinds = ("Pod",)

    def reconcile(self, job: dict[str, Any]) -> float | None:
        name = job["metadata"]["name"]
        ns = job["metadata"].get("namespace", "default")
        key = self.key_of(job)
        status = job["status"]

        if is_finished(status):
            return self._reconcile_finished(job)

        errs = validate_job(job)
        if errs:
            self._fail(job, "InvalidSpec", "; ".join(errs))
            return None

        if not status.get("conditions"):
            self.store.mutate(JOB_KIND, name, lambda o: (
                o["status"].update(startTime=time.time()),
                set_condition(o["status"], JobConditionType.CREATED,
                              "JobCreated", f"JAXJob {name} is created.")),
                ns)
            return 0.0

        run_policy = job["spec"].get("runPolicy", {})
        deadline = run_policy.get("activeDeadlineSeconds")
        if deadline and time.time() - status.get("startTime", 0) > deadline:
            self._fail(job, "DeadlineExceeded",
                       f"job ran longer than activeDeadlineSeconds={deadline}")
            return None

        if not self.expectations.satisfied(key):
            return 0.1  # stale view: only observe, don't create/delete

        self._ensure_pod_group(job)
        pods = self.store.list("Pod", ns, labels={JOB_NAME_LABEL: name})
        by_slot = {(p["metadata"]["labels"][REPLICA_TYPE_LABEL],
                    int(p["metadata"]["labels"][REPLICA_INDEX_LABEL])): p
                   for p in pods}

        order = _replica_order(job["spec"])
        total_restarts = status.get("restartCount", 0)
        backoff_limit = run_policy.get("backoffLimit")  # unset = unlimited
        restarted = False

        # -- pod lifecycle: create missing, restart/flag failed ---------------
        for rank, (rtype, idx) in enumerate(order):
            pod = by_slot.get((rtype, idx))
            if pod is None:
                self._create_pod(job, rtype, idx, rank, len(order))
                continue
            phase = pod["status"].get("phase")
            if phase == "Failed":
                policy = job["spec"]["replicaSpecs"][rtype].get(
                    "restartPolicy", "Never")
                exit_code = pod["status"].get("exitCode", 1)
                # subprocess pods killed by a signal report -signum; treat
                # them like the >=128 shell convention (SIGKILL'd/preempted
                # = retryable under ExitCode)
                retryable = (policy in ("OnFailure", "Always")
                             or (policy == "ExitCode"
                                 and (exit_code >= 128 or exit_code < 0)))
                if not retryable:
                    self._fail(job, "PodFailed",
                               f"pod {pod['metadata']['name']} failed with "
                               f"exit code {exit_code} "
                               f"(restartPolicy={policy})")
                    return None
                if backoff_limit is not None and total_restarts >= backoff_limit:
                    self._fail(job, "BackoffLimitExceeded",
                               f"restartCount {total_restarts} reached "
                               f"backoffLimit {backoff_limit}")
                    return None
                total_restarts += 1
                restarted = True
                self.expectations.expect_deletions(key, 1)
                self.store.try_delete("Pod", pod["metadata"]["name"], ns)
            elif phase == "Succeeded" and job["spec"]["replicaSpecs"][rtype].get(
                    "restartPolicy") == "Always":
                self.expectations.expect_deletions(key, 1)
                self.store.try_delete("Pod", pod["metadata"]["name"], ns)

        # -- status aggregation -----------------------------------------------
        pods = self.store.list("Pod", ns, labels={JOB_NAME_LABEL: name})
        replica_statuses: dict[str, dict[str, int]] = {}
        for rtype in job["spec"]["replicaSpecs"]:
            rs = {"active": 0, "succeeded": 0, "failed": 0}
            for p in pods:
                if p["metadata"]["labels"][REPLICA_TYPE_LABEL] != rtype:
                    continue
                phase = p["status"].get("phase", "Pending")
                if phase == "Succeeded":
                    rs["succeeded"] += 1
                elif phase == "Failed":
                    rs["failed"] += 1
                else:
                    rs["active"] += 1
            replica_statuses[rtype] = rs

        def write(o):
            o["status"]["replicaStatuses"] = replica_statuses
            o["status"]["restartCount"] = total_restarts
            if restarted:
                set_condition(o["status"], JobConditionType.RESTARTING,
                              "PodRestarting", "failed replica restarting")
            elif any(rs["active"] for rs in replica_statuses.values()):
                running = sum(
                    1 for p in pods if p["status"].get("phase") == "Running")
                if running == len(order):
                    set_condition(o["status"], JobConditionType.RUNNING,
                                  "JobRunning", "all replicas running")
        self.store.mutate(JOB_KIND, name, write, ns)

        # -- success ----------------------------------------------------------
        if self._check_success(job, replica_statuses, order):
            self.store.mutate(JOB_KIND, name, lambda o: (
                o["status"].update(completionTime=time.time()),
                set_condition(o["status"], JobConditionType.SUCCEEDED,
                              "JobSucceeded", "success policy satisfied")),
                ns)
            self._clean_pods(job)
            return 0.0
        return 0.5 if restarted else None

    # -- helpers --------------------------------------------------------------

    def _check_success(self, job, replica_statuses, order) -> bool:
        policy = job["spec"].get("successPolicy", "Worker0")
        if policy == "AllWorkers":
            return all(
                rs["succeeded"] >= job["spec"]["replicaSpecs"][rt].get(
                    "replicas", 1)
                for rt, rs in replica_statuses.items())
        rtype0, idx0 = order[0]
        pod = self.store.try_get(
            "Pod", self._pod_name(job, rtype0, idx0),
            job["metadata"].get("namespace", "default"))
        return pod is not None and pod["status"].get("phase") == "Succeeded"

    @staticmethod
    def _pod_name(job, rtype: str, idx: int) -> str:
        return f"{job['metadata']['name']}-{rtype}-{idx}"

    def _coordinator_port(self, job) -> int:
        return _BASE_PORT + int(job["metadata"]["uid"][:4], 16) % 8000

    def _create_pod(self, job, rtype: str, idx: int, rank: int,
                    world: int) -> None:
        ns = job["metadata"].get("namespace", "default")
        name = job["metadata"]["name"]
        rspec = job["spec"]["replicaSpecs"][rtype]
        template = rspec["template"]
        env = dict(template.get("env", {}))
        env.update({
            "KTPU_JOB_NAME": name,
            "KTPU_NAMESPACE": ns,
            "KTPU_REPLICA_TYPE": rtype,
            "KTPU_REPLICA_INDEX": str(idx),
            "KTPU_NUM_PROCESSES": str(world),
            "KTPU_PROCESS_ID": str(rank),
            "KTPU_COORDINATOR_ADDRESS":
                f"127.0.0.1:{self._coordinator_port(job)}",
        })
        pod = new_resource(
            "Pod", self._pod_name(job, rtype, idx),
            spec={**{k: v for k, v in template.items() if k != "env"},
                  "env": env},
            namespace=ns,
            labels={JOB_NAME_LABEL: name, REPLICA_TYPE_LABEL: rtype,
                    REPLICA_INDEX_LABEL: str(idx), GROUP_LABEL: name},
            owner=job)
        self.expectations.expect_creations(self.key_of(job), 1)
        try:
            self.store.create(pod)
        except AlreadyExistsError:
            self.expectations.creation_observed(self.key_of(job))

    def _ensure_pod_group(self, job) -> None:
        ns = job["metadata"].get("namespace", "default")
        name = job["metadata"]["name"]
        if self.store.try_get("PodGroup", name, ns) is not None:
            return
        total = sum(r.get("replicas", 1)
                    for r in job["spec"]["replicaSpecs"].values())
        min_avail = (job["spec"].get("runPolicy", {})
                     .get("schedulingPolicy", {}).get("minAvailable", total))
        pg = new_resource("PodGroup", name,
                          spec={"minAvailable": min_avail},
                          namespace=ns, owner=job)
        try:
            self.store.create(pg)
        except AlreadyExistsError:
            pass

    def _fail(self, job, reason: str, message: str) -> None:
        ns = job["metadata"].get("namespace", "default")
        try:
            self.store.mutate(JOB_KIND, job["metadata"]["name"], lambda o: (
                o["status"].update(completionTime=time.time()),
                set_condition(o["status"], JobConditionType.FAILED,
                              reason, message)), ns)
        except NotFoundError:
            return
        self._clean_pods(job, failed=True)

    def _clean_pods(self, job, failed: bool = False) -> None:
        """cleanPodPolicy at completion: Running (default) deletes only
        still-active pods; All deletes everything; None keeps pods for
        debugging."""
        policy = job["spec"].get("runPolicy", {}).get("cleanPodPolicy",
                                                      "Running")
        ns = job["metadata"].get("namespace", "default")
        for p in self.store.list(
                "Pod", ns, labels={JOB_NAME_LABEL: job["metadata"]["name"]}):
            active = p["status"].get("phase", "Pending") not in ("Succeeded",
                                                                 "Failed")
            # All: delete everything. Running: delete still-active pods.
            # None: keep pods for debugging — but a failed job must still
            # release its active pods (and their devices).
            if (policy == "All" or (policy == "Running" and active)
                    or (policy == "None" and failed and active)):
                self.store.try_delete("Pod", p["metadata"]["name"], ns)

    def _reconcile_finished(self, job) -> float | None:
        ttl = job["spec"].get("runPolicy", {}).get("ttlSecondsAfterFinished")
        if ttl is None:
            return None
        ns = job["metadata"].get("namespace", "default")
        done_at = job["status"].get("completionTime", time.time())
        remaining = done_at + ttl - time.time()
        if remaining > 0:
            return remaining
        self.store.delete_owned_by(job)
        self.store.try_delete(JOB_KIND, job["metadata"]["name"], ns)
        return None
