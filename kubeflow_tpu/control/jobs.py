"""JAXJob controller — the training-operator + kubeflow/common reconcile
engine (SURVEY.md §2.2, §3.1) rebuilt around JAX processes.

Spec shape (PyTorchJob-compatible skeleton):

    kind: JAXJob
    spec:
      runPolicy:
        backoffLimit: 3              # total restarts before Failed
        activeDeadlineSeconds: 600
        ttlSecondsAfterFinished: 5
        cleanPodPolicy: Running      # Running | All | None
        schedulingPolicy: {minAvailable: N}   # gang size, default Σreplicas
      successPolicy: Worker0         # Worker0 | AllWorkers
      elasticPolicy:                 # PyTorch-elastic analog (§5.3)
        minReplicas: 2               # gang shrinks toward this on worker loss
        maxReplicas: 4
      failureDetection:              # heartbeat liveness (rendezvous svc)
        heartbeatTtlSeconds: 10      # silent rank -> pod Failed(HeartbeatLost)
      replicaSpecs:
        worker:
          replicas: 4
          restartPolicy: OnFailure   # Never | OnFailure | Always | ExitCode
          template:
            backend: thread | subprocess
            target: <registered fn> | argv: [...] | command: "python -c ..."
            env: {...}
            resources: {tpu: 1, cpu: 1}

Where the reference injects MASTER_ADDR/WORLD_SIZE/RANK for torch's TCPStore
rendezvous, this controller injects KTPU_COORDINATOR_ADDRESS /
KTPU_NUM_PROCESSES / KTPU_PROCESS_ID for `jax.distributed.initialize`
(SURVEY.md §5.8) — consumed via kubeflow_tpu.runtime.bootstrap.

ExitCode restart policy follows the reference's convention: exit codes >=128
(SIGKILL'd, preempted) are retryable; 1–127 are permanent failures.
"""

from __future__ import annotations

import time
from typing import Any

from kubeflow_tpu.control.conditions import (JobConditionType, is_finished,
                                             set_condition)
from kubeflow_tpu.control.controller import Controller
from kubeflow_tpu.control.scheduler import GROUP_LABEL
from kubeflow_tpu.control.store import (AlreadyExistsError, NotFoundError,
                                        new_resource)
from kubeflow_tpu.utils.metrics import (JOBS_CREATED, JOBS_FAILED,
                                        JOBS_RESTARTED, JOBS_SUCCESSFUL)

JOB_KIND = "JAXJob"
JOB_NAME_LABEL = "kubeflow-tpu/job-name"
REPLICA_TYPE_LABEL = "kubeflow-tpu/replica-type"
REPLICA_INDEX_LABEL = "kubeflow-tpu/replica-index"
GANG_EPOCH_LABEL = "kubeflow-tpu/gang-epoch"

_BASE_PORT = 47000


def validate_job(job: dict[str, Any]) -> list[str]:
    """Table-driven spec validation (admission-webhook analog)."""
    errs = []
    spec = job.get("spec", {})
    replicas = spec.get("replicaSpecs", {})
    if not replicas:
        errs.append("spec.replicaSpecs must define at least one replica type")
    for rtype, rspec in replicas.items():
        n = rspec.get("replicas", 1)
        if not isinstance(n, int) or n < 1:
            errs.append(f"replicaSpecs.{rtype}.replicas must be >= 1")
        rp = rspec.get("restartPolicy", "Never")
        if rp not in ("Never", "OnFailure", "Always", "ExitCode"):
            errs.append(f"replicaSpecs.{rtype}.restartPolicy invalid: {rp}")
        if "template" not in rspec:
            errs.append(f"replicaSpecs.{rtype}.template is required")
    run = spec.get("runPolicy", {})
    if run.get("backoffLimit", 0) < 0:
        errs.append("runPolicy.backoffLimit must be >= 0")
    sp = spec.get("successPolicy", "Worker0")
    if sp not in ("Worker0", "AllWorkers"):
        errs.append(f"successPolicy invalid: {sp}")
    elastic = spec.get("elasticPolicy")
    if elastic is not None:
        lo = elastic.get("minReplicas", 1)
        hi = elastic.get("maxReplicas",
                         replicas.get("worker", {}).get("replicas", 1))
        if "worker" not in replicas:
            errs.append("elasticPolicy requires a worker replica type")
        if lo < 1 or hi < lo:
            errs.append("elasticPolicy needs 1 <= minReplicas <= maxReplicas")
    fd = spec.get("failureDetection")
    if fd is not None and fd.get("heartbeatTtlSeconds", 1) <= 0:
        errs.append("failureDetection.heartbeatTtlSeconds must be > 0")
    return errs


def _effective_replicas(job: dict[str, Any]) -> dict[str, int]:
    """Replica counts after elastic resizing (status.elasticReplicas is the
    current gang size the controller converged on — the PyTorch-elastic
    min/max analog, SURVEY.md §5.3)."""
    spec = job["spec"]
    elastic = spec.get("elasticPolicy")
    out: dict[str, int] = {}
    for rtype, rspec in spec.get("replicaSpecs", {}).items():
        n = rspec.get("replicas", 1)
        if elastic and rtype == "worker":
            n = min(n, elastic.get("maxReplicas", n))
            n = job["status"].get("elasticReplicas", n)
        out[rtype] = n
    return out


def _replica_order(spec: dict[str, Any],
                   replicas: dict[str, int] | None = None,
                   priority: tuple[str, ...] = ("master",)
                   ) -> list[tuple[str, int]]:
    """Deterministic global process ranking: replica types sorted (priority
    roles first — master/chief/launcher — then alphabetical), then index —
    the genClusterSpec ordering analog."""
    order: list[tuple[str, int]] = []

    def key(t: str):
        return (priority.index(t) if t in priority else len(priority), t)

    rtypes = sorted(spec.get("replicaSpecs", {}), key=key)
    for rtype in rtypes:
        n = (replicas or {}).get(
            rtype, spec["replicaSpecs"][rtype].get("replicas", 1))
        for i in range(n):
            order.append((rtype, i))
    return order


class JAXJobController(Controller):
    """Also the base for the framework-compat job kinds (TFJob, PyTorchJob,
    ... — control/frameworks.py): subclasses override `kind`, the role
    attributes, and `cluster_env` (the SetClusterSpec analog); every other
    semantic — gang, expectations, RunPolicy, elastic, heartbeats — is
    shared, mirroring how the reference hosts all job controllers on one
    kubeflow/common engine (SURVEY.md §2.2)."""

    kind = JOB_KIND
    owned_kinds = ("Pod",)
    # rank-0-first role ordering (genClusterSpec analog); subclasses override
    role_priority: tuple[str, ...] = ("master",)
    # allowed replica-type names; None = any (JAXJob is schema-free)
    roles: tuple[str, ...] | None = None
    # roles capped at replicas=1 (a second master is a spec error); empty for
    # JAXJob — it is schema-free, and its admission validator (validate_job)
    # must stay in lockstep with reconcile-time validation
    singleton_roles: tuple[str, ...] = ()
    # successPolicy=Worker0 gates on index 0 of the first of these roles
    # present in the spec (falls back to global rank 0)
    success_roles: tuple[str, ...] = ("master", "worker")

    def __init__(self, cluster):
        super().__init__(cluster)
        # per-job rendezvous/heartbeat coordinators (failureDetection jobs)
        self._coordinators: dict[str, Any] = {}

    @classmethod
    def validate(cls, job: dict[str, Any]) -> list[str]:
        """validate_job + per-kind role schema (the per-kind validating
        webhook analog)."""
        errs = validate_job(job)
        replicas = job.get("spec", {}).get("replicaSpecs", {})
        for rtype, rspec in replicas.items():
            if cls.roles is not None and rtype not in cls.roles:
                errs.append(
                    f"{cls.kind} does not allow replica type {rtype!r} "
                    f"(allowed: {', '.join(cls.roles)})")
            if rtype in cls.singleton_roles and rspec.get("replicas", 1) > 1:
                errs.append(
                    f"replicaSpecs.{rtype}.replicas must be 1 for {cls.kind}")
        return errs

    def reconcile(self, job: dict[str, Any]) -> float | None:
        name = job["metadata"]["name"]
        ns = job["metadata"].get("namespace", "default")
        key = self.key_of(job)
        status = job["status"]

        if is_finished(status):
            return self._reconcile_finished(job)

        errs = self.validate(job)
        if errs:
            self._fail(job, "InvalidSpec", "; ".join(errs))
            return None

        if not status.get("conditions"):
            self.store.mutate(self.kind, name, lambda o: (
                o["status"].update(startTime=time.time()),
                set_condition(o["status"], JobConditionType.CREATED,
                              "JobCreated",
                              f"{self.kind} {name} is created.")),
                ns)
            JOBS_CREATED.inc(kind=self.kind)
            return 0.0

        run_policy = job["spec"].get("runPolicy", {})
        deadline = run_policy.get("activeDeadlineSeconds")
        if deadline and time.time() - status.get("startTime", 0) > deadline:
            self._fail(job, "DeadlineExceeded",
                       f"job ran longer than activeDeadlineSeconds={deadline}")
            return None

        if not self.expectations.satisfied(key):
            return 0.1  # stale view: only observe, don't create/delete

        eff = _effective_replicas(job)
        epoch = status.get("gangEpoch", 0)
        self._ensure_pod_group(job, eff)
        self._detect_heartbeat_failures(job, eff, epoch)
        pods = self.store.list("Pod", ns, labels={JOB_NAME_LABEL: name})

        # stale-gang cleanup: pods from a previous gang epoch (pre-resize
        # world) or beyond the current replica count are torn down wholesale
        # — their KTPU_NUM_PROCESSES/rank env no longer describes the gang
        live_pods = []
        stale_torn_down = False
        for p in pods:
            labels = p["metadata"]["labels"]
            stale = (int(labels.get(GANG_EPOCH_LABEL, "0")) != epoch
                     or int(labels[REPLICA_INDEX_LABEL])
                     >= eff.get(labels[REPLICA_TYPE_LABEL], 0))
            if stale:
                stale_torn_down = True
                self.expectations.expect_deletions(key, 1)
                self.store.try_delete("Pod", p["metadata"]["name"], ns)
            else:
                live_pods.append(p)
        pods = live_pods
        if stale_torn_down:
            # gang DOWN before gang UP: creating the new epoch's pods while
            # old-epoch pods still run lets the scheduler count the stale
            # pods toward the new gang and bind a partial epoch (observed:
            # one new pod binds alone, finishes, and the rest deadlock at
            # WaitingForGang). Finish the teardown, create next pass.
            return 0.05
        by_slot = {(p["metadata"]["labels"][REPLICA_TYPE_LABEL],
                    int(p["metadata"]["labels"][REPLICA_INDEX_LABEL])): p
                   for p in pods}

        order = _replica_order(job["spec"], eff, self.role_priority)
        total_restarts = status.get("restartCount", 0)
        backoff_limit = run_policy.get("backoffLimit")  # unset = unlimited
        restarted = False

        # -- pod lifecycle: create missing, restart/flag failed ---------------
        for rank, (rtype, idx) in enumerate(order):
            pod = by_slot.get((rtype, idx))
            if pod is None:
                self._create_pod(job, rtype, idx, rank, len(order), epoch)
                continue
            phase = pod["status"].get("phase")
            if phase == "Failed":
                policy = job["spec"]["replicaSpecs"][rtype].get(
                    "restartPolicy", "Never")
                exit_code = pod["status"].get("exitCode", 1)
                # subprocess pods killed by a signal report -signum; treat
                # them like the >=128 shell convention (SIGKILL'd/preempted
                # = retryable under ExitCode)
                retryable = (policy in ("OnFailure", "Always")
                             or (policy == "ExitCode"
                                 and (exit_code >= 128 or exit_code < 0)))
                if not retryable:
                    self._fail(job, "PodFailed",
                               f"pod {pod['metadata']['name']} failed with "
                               f"exit code {exit_code} "
                               f"(restartPolicy={policy})")
                    return None
                if backoff_limit is not None and total_restarts >= backoff_limit:
                    self._fail(job, "BackoffLimitExceeded",
                               f"restartCount {total_restarts} reached "
                               f"backoffLimit {backoff_limit}")
                    return None
                total_restarts += 1
                restarted = True
                JOBS_RESTARTED.inc(kind=self.kind)
                elastic = job["spec"].get("elasticPolicy")
                if (elastic and rtype == "worker"
                        and eff["worker"] > elastic.get("minReplicas", 1)):
                    # elastic shrink: restart the WHOLE gang one worker
                    # smaller (checkpoint-restore carries the training state,
                    # §5.3) instead of waiting for the lost capacity
                    self.store.mutate(self.kind, name, lambda o: (
                        # a shrink supersedes any grow in flight: disarm the
                        # grow watchdog or it would "revert" the new gang
                        o["status"].pop("lastStableReplicas", None),
                        o["status"].update(
                            elasticReplicas=eff["worker"] - 1,
                            gangEpoch=epoch + 1,
                            lastResizeTime=time.time(),
                            restartCount=total_restarts),
                        set_condition(o["status"],
                                      JobConditionType.RESTARTING,
                                      "ElasticResize",
                                      f"gang shrinking to "
                                      f"{eff['worker'] - 1} workers")), ns)
                    return 0.1  # next pass tears down the stale epoch
                self.expectations.expect_deletions(key, 1)
                self.store.try_delete("Pod", pod["metadata"]["name"], ns)
            elif phase == "Succeeded" and job["spec"]["replicaSpecs"][rtype].get(
                    "restartPolicy") == "Always":
                self.expectations.expect_deletions(key, 1)
                self.store.try_delete("Pod", pod["metadata"]["name"], ns)

        # -- status aggregation -----------------------------------------------
        pods = self.store.list("Pod", ns, labels={JOB_NAME_LABEL: name})
        replica_statuses: dict[str, dict[str, int]] = {}
        for rtype in job["spec"]["replicaSpecs"]:
            rs = {"active": 0, "succeeded": 0, "failed": 0}
            for p in pods:
                if p["metadata"]["labels"][REPLICA_TYPE_LABEL] != rtype:
                    continue
                phase = p["status"].get("phase", "Pending")
                if phase == "Succeeded":
                    rs["succeeded"] += 1
                elif phase == "Failed":
                    rs["failed"] += 1
                else:
                    rs["active"] += 1
            replica_statuses[rtype] = rs

        def write(o):
            o["status"]["replicaStatuses"] = replica_statuses
            o["status"]["restartCount"] = total_restarts
            if restarted:
                set_condition(o["status"], JobConditionType.RESTARTING,
                              "PodRestarting", "failed replica restarting")
            elif any(rs["active"] for rs in replica_statuses.values()):
                running = sum(
                    1 for p in pods if p["status"].get("phase") == "Running")
                if running == len(order):
                    set_condition(o["status"], JobConditionType.RUNNING,
                                  "JobRunning", "all replicas running")
        self.store.mutate(self.kind, name, write, ns)

        # -- success ----------------------------------------------------------
        if self._check_success(job, replica_statuses, order):
            self.store.mutate(self.kind, name, lambda o: (
                o["status"].update(completionTime=time.time()),
                set_condition(o["status"], JobConditionType.SUCCEEDED,
                              "JobSucceeded", "success policy satisfied")),
                ns)
            JOBS_SUCCESSFUL.inc(kind=self.kind)
            self._clean_pods(job)
            self._stop_coordinator(key)
            return 0.0
        # -- elastic grow -----------------------------------------------------
        # the rejoin path (⊘ PyTorch ElasticPolicy rdzv re-admit, SURVEY.md
        # §5.3): a shrunken gang that has run stably for growAfterSeconds
        # grows back toward min(spec replicas, maxReplicas) one worker at a
        # time — IF the device inventory can actually place it. Same
        # mechanism as shrink: whole-gang restart at the new world size,
        # checkpoint-restore carries the training state across the resize.
        grow_requeue = self._maybe_grow(job, eff, epoch, restarted)

        hb_requeue = None
        if job["spec"].get("failureDetection"):
            # poll cadence for the heartbeat detector even when nothing else
            # changes — dead ranks only surface via this reconcile path
            ttl = job["spec"]["failureDetection"].get(
                "heartbeatTtlSeconds", 10.0)
            hb_requeue = min(max(ttl / 2.0, 0.1), 2.0)
        # a slow grow poll must never slacken the heartbeat cadence (a
        # capacity-blocked grow would otherwise delay dead-rank detection
        # by up to growAfterSeconds)
        candidates = [r for r in (grow_requeue, hb_requeue) if r is not None]
        if candidates:
            return min(candidates)
        return 0.5 if restarted else None

    def _maybe_grow(self, job, eff, epoch, restarted) -> float | None:
        elastic = job["spec"].get("elasticPolicy")
        if not elastic or restarted or "worker" not in eff:
            return None
        ns = job["metadata"].get("namespace", "default")
        name = job["metadata"]["name"]
        status = job["status"]
        # grow-in-flight watchdog (check-then-act hole: between fits() and
        # the new gang binding, another job can claim the freed chips and
        # park the grown gang at WaitingForGang forever). A committed grow
        # records the last-known-good world; if the grown gang hasn't fully
        # bound within growTimeoutSeconds, revert to it.
        pending_stable = status.get("lastStableReplicas")
        if pending_stable is not None:
            pods = self.store.list("Pod", ns, labels={JOB_NAME_LABEL: name})
            running = [p for p in pods
                       if p["status"].get("phase") == "Running"]
            if len(running) >= sum(eff.values()):
                # grown gang bound and running: the resize is confirmed
                self.store.mutate(self.kind, name, lambda o: o[
                    "status"].pop("lastStableReplicas", None), ns)
            else:
                timeout = elastic.get("growTimeoutSeconds", 30.0)
                waited = time.time() - status.get("lastResizeTime", 0)
                if waited > timeout:
                    self.store.mutate(self.kind, name, lambda o: (
                        o["status"].pop("lastStableReplicas", None),
                        o["status"].update(
                            elasticReplicas=pending_stable,
                            gangEpoch=epoch + 1,
                            lastResizeTime=time.time()),
                        set_condition(o["status"],
                                      JobConditionType.RESTARTING,
                                      "ElasticGrowReverted",
                                      f"grown gang failed to bind in "
                                      f"{timeout:.0f}s; reverting to "
                                      f"{pending_stable} workers")), ns)
                    return 0.1
                return min(max(timeout - waited, 0.1), 1.0)
        spec_replicas = job["spec"]["replicaSpecs"]["worker"].get(
            "replicas", 1)
        target = min(spec_replicas, elastic.get("maxReplicas", spec_replicas))
        if eff["worker"] >= target:
            return None
        # stability gate: no resize/restart churn for growAfterSeconds
        grow_after = elastic.get("growAfterSeconds", 3.0)
        last = status.get("lastResizeTime") or status.get("startTime", 0)
        if time.time() - last < grow_after:
            return min(grow_after, 1.0)  # re-check when the window elapses
        # the whole current gang must be Running (not mid-recovery)
        pods = self.store.list("Pod", ns, labels={JOB_NAME_LABEL: name})
        running = [p for p in pods
                   if p["status"].get("phase") == "Running"]
        if len(running) < sum(eff.values()):
            return None
        # capacity gate: only grow if the scheduler could place one more
        # worker right now (otherwise the gang restart would deadlock
        # Pending — the all-or-nothing hazard the PodGroup exists for)
        template = job["spec"]["replicaSpecs"]["worker"].get("template", {})
        request = template.get("resources", {"cpu": 1})
        inventory = getattr(self.cluster, "inventory", None)
        if inventory is not None and not inventory.fits([request]):
            return grow_after  # capacity may free later; poll slowly
        new_world = eff["worker"] + 1
        self.store.mutate(self.kind, name, lambda o: (
            o["status"].update(
                elasticReplicas=new_world,
                gangEpoch=epoch + 1,
                lastResizeTime=time.time(),
                # last-known-good world for the grow watchdog above
                lastStableReplicas=eff["worker"]),
            set_condition(o["status"], JobConditionType.RESTARTING,
                          "ElasticResize",
                          f"gang growing to {new_world} workers")), ns)
        return 0.1  # next pass tears down the stale epoch and re-creates

    # -- helpers --------------------------------------------------------------

    def _check_success(self, job, replica_statuses, order) -> bool:
        policy = job["spec"].get("successPolicy", "Worker0")
        if policy == "AllWorkers":
            eff = _effective_replicas(job)
            return all(rs["succeeded"] >= eff.get(rt, 1)
                       for rt, rs in replica_statuses.items())
        rtype0, idx0 = order[0]
        for role in self.success_roles:
            if role in job["spec"].get("replicaSpecs", {}):
                rtype0, idx0 = role, 0
                break
        pod = self.store.try_get(
            "Pod", self._pod_name(job, rtype0, idx0),
            job["metadata"].get("namespace", "default"))
        return pod is not None and pod["status"].get("phase") == "Succeeded"

    @staticmethod
    def _pod_name(job, rtype: str, idx: int) -> str:
        return f"{job['metadata']['name']}-{rtype}-{idx}"

    def _coordinator_port(self, job) -> int:
        return _BASE_PORT + int(job["metadata"]["uid"][:4], 16) % 8000

    def cluster_env(self, job, rtype: str, idx: int, rank: int,
                    world: int) -> dict[str, str]:
        """The SetClusterSpec analog: per-pod rendezvous env. JAXJob hands
        out the jax.distributed.initialize triple; framework kinds override
        with TF_CONFIG / MASTER_ADDR / DMLC_* / PADDLE_* shapes."""
        return {
            "KTPU_COORDINATOR_ADDRESS":
                f"127.0.0.1:{self._coordinator_port(job)}",
        }

    def _create_pod(self, job, rtype: str, idx: int, rank: int,
                    world: int, epoch: int = 0) -> None:
        ns = job["metadata"].get("namespace", "default")
        name = job["metadata"]["name"]
        rspec = job["spec"]["replicaSpecs"][rtype]
        template = rspec["template"]
        env = dict(template.get("env", {}))
        env.update({
            "KTPU_JOB_NAME": name,
            "KTPU_NAMESPACE": ns,
            "KTPU_REPLICA_TYPE": rtype,
            "KTPU_REPLICA_INDEX": str(idx),
            "KTPU_NUM_PROCESSES": str(world),
            "KTPU_PROCESS_ID": str(rank),
            "KTPU_GANG_EPOCH": str(epoch),
        })
        env.update(self.cluster_env(job, rtype, idx, rank, world))
        rdv = self._coordinators.get(self.key_of(job))
        if rdv is not None:
            fd = job["spec"].get("failureDetection", {})
            env["KTPU_RENDEZVOUS_ADDRESS"] = rdv.address
            env["KTPU_HEARTBEAT_TTL"] = str(
                fd.get("heartbeatTtlSeconds", 10.0))
        pod = new_resource(
            "Pod", self._pod_name(job, rtype, idx),
            spec={**{k: v for k, v in template.items() if k != "env"},
                  "env": env},
            namespace=ns,
            labels={JOB_NAME_LABEL: name, REPLICA_TYPE_LABEL: rtype,
                    REPLICA_INDEX_LABEL: str(idx), GROUP_LABEL: name,
                    GANG_EPOCH_LABEL: str(epoch)},
            owner=job)
        self.expectations.expect_creations(self.key_of(job), 1)
        try:
            self.store.create(pod)
        except AlreadyExistsError:
            self.expectations.creation_observed(self.key_of(job))

    def _ensure_pod_group(self, job, eff: dict[str, int] | None = None) -> None:
        ns = job["metadata"].get("namespace", "default")
        name = job["metadata"]["name"]
        total = sum((eff or _effective_replicas(job)).values())
        min_avail = (job["spec"].get("runPolicy", {})
                     .get("schedulingPolicy", {}).get("minAvailable", total))
        existing = self.store.try_get("PodGroup", name, ns)
        if existing is not None:
            if existing["spec"].get("minAvailable") != min_avail:
                # elastic resize shrank the gang — the all-or-nothing
                # threshold must follow or the scheduler waits forever
                self.store.mutate(
                    "PodGroup", name,
                    lambda o: o["spec"].update(minAvailable=min_avail), ns)
            return
        pg = new_resource("PodGroup", name,
                          spec={"minAvailable": min_avail},
                          namespace=ns, owner=job)
        try:
            self.store.create(pg)
        except AlreadyExistsError:
            pass

    # -- heartbeat failure detection (§5.3) -----------------------------------

    def _detect_heartbeat_failures(self, job, eff: dict[str, int],
                                   epoch: int) -> None:
        """Run a rendezvous/heartbeat coordinator for jobs that ask for it
        and convert dead ranks into pod failures, which then flow through
        the ordinary restart/elastic machinery."""
        fd = job["spec"].get("failureDetection")
        if not fd:
            return
        key = self.key_of(job)
        srv = self._coordinators.get(key)
        if srv is None:
            from kubeflow_tpu.runtime.rendezvous import make_coordinator

            srv = make_coordinator(
                hb_ttl_s=fd.get("heartbeatTtlSeconds", 10.0))
            self._coordinators[key] = srv
            return  # pods created after this pass get the address injected
        try:
            from kubeflow_tpu.runtime.rendezvous import RendezvousClient

            client = RendezvousClient(srv.address, timeout=2.0)
            try:
                _, _, dead = client.status(self._gang_id(job, epoch))
            finally:
                client.close()
        except OSError:
            return
        ns = job["metadata"].get("namespace", "default")
        order = _replica_order(job["spec"], eff, self.role_priority)
        for rank in dead:
            if rank >= len(order):
                continue
            rtype, idx = order[rank]
            pod = self.store.try_get("Pod", self._pod_name(job, rtype, idx),
                                     ns)
            if pod is None or pod["status"].get("phase") != "Running":
                continue
            self.store.mutate(
                "Pod", pod["metadata"]["name"],
                lambda o: o["status"].update(
                    phase="Failed", exitCode=137, reason="HeartbeatLost"),
                ns)

    @staticmethod
    def _gang_id(job, epoch: int) -> str:
        """Rendezvous job id: one barrier group per gang epoch, so a resized
        gang re-rendezvouses cleanly instead of colliding with dead ranks."""
        return f"{job['metadata']['name']}/{epoch}"

    def _stop_coordinator(self, key: str) -> None:
        srv = self._coordinators.pop(key, None)
        if srv is not None:
            srv.stop()

    def reconcile_deleted(self, name: str, namespace: str):
        self._stop_coordinator(f"{namespace}/{name}")
        return None

    def stop(self) -> None:
        super().stop()
        for key in list(self._coordinators):
            self._stop_coordinator(key)

    def _fail(self, job, reason: str, message: str) -> None:
        ns = job["metadata"].get("namespace", "default")
        JOBS_FAILED.inc(kind=self.kind, reason=reason)
        self._stop_coordinator(self.key_of(job))
        try:
            self.store.mutate(self.kind, job["metadata"]["name"], lambda o: (
                o["status"].update(completionTime=time.time()),
                set_condition(o["status"], JobConditionType.FAILED,
                              reason, message)), ns)
        except NotFoundError:
            return
        self._clean_pods(job, failed=True)

    def _clean_pods(self, job, failed: bool = False) -> None:
        """cleanPodPolicy at completion: Running (default) deletes only
        still-active pods; All deletes everything; None keeps pods for
        debugging."""
        policy = job["spec"].get("runPolicy", {}).get("cleanPodPolicy",
                                                      "Running")
        ns = job["metadata"].get("namespace", "default")
        for p in self.store.list(
                "Pod", ns, labels={JOB_NAME_LABEL: job["metadata"]["name"]}):
            active = p["status"].get("phase", "Pending") not in ("Succeeded",
                                                                 "Failed")
            # All: delete everything. Running: delete still-active pods.
            # None: keep pods for debugging — but a failed job must still
            # release its active pods (and their devices).
            if (policy == "All" or (policy == "Running" and active)
                    or (policy == "None" and failed and active)):
                self.store.try_delete("Pod", p["metadata"]["name"], ns)

    def _reconcile_finished(self, job) -> float | None:
        ttl = job["spec"].get("runPolicy", {}).get("ttlSecondsAfterFinished")
        if ttl is None:
            return None
        ns = job["metadata"].get("namespace", "default")
        done_at = job["status"].get("completionTime", time.time())
        remaining = done_at + ttl - time.time()
        if remaining > 0:
            return remaining
        self.store.delete_owned_by(job)
        self.store.try_delete(self.kind, job["metadata"]["name"], ns)
        return None
