"""Job status conditions — the kubeflow/common `JobCondition` machinery
(SURVEY.md §2.2, `common/job.go` / `util/status.go` analogs).

A job's `status.conditions` is an ordered list; exactly one condition is the
*latest* truth but history is preserved (the reference keeps prior conditions
with status flipped to False). Lifecycle: Created → Running → (Restarting ⇄
Running) → Succeeded | Failed. Succeeded/Failed are terminal.
"""

from __future__ import annotations

import time
from typing import Any


class JobConditionType:
    CREATED = "Created"
    RUNNING = "Running"
    RESTARTING = "Restarting"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    SUSPENDED = "Suspended"


_TERMINAL = (JobConditionType.SUCCEEDED, JobConditionType.FAILED)
# Conditions mutually exclusive with a newly set one (flipped to False).
_EXCLUSIVE = {
    JobConditionType.RUNNING: {JobConditionType.RESTARTING,
                               JobConditionType.SUSPENDED},
    JobConditionType.RESTARTING: {JobConditionType.RUNNING},
    JobConditionType.SUSPENDED: {JobConditionType.RUNNING},
    JobConditionType.SUCCEEDED: {JobConditionType.RUNNING,
                                 JobConditionType.RESTARTING},
    JobConditionType.FAILED: {JobConditionType.RUNNING,
                              JobConditionType.RESTARTING},
}


def set_condition(status: dict[str, Any], ctype: str, reason: str = "",
                  message: str = "") -> None:
    conds = status.setdefault("conditions", [])
    now = time.time()
    for c in conds:
        if c["type"] == ctype:
            if c["status"] == "True" and c["reason"] == reason:
                return  # no-op; avoid resourceVersion churn
            c.update(status="True", reason=reason, message=message,
                     lastTransitionTime=now)
            break
    else:
        conds.append({"type": ctype, "status": "True", "reason": reason,
                      "message": message, "lastTransitionTime": now})
    for c in conds:
        if c["type"] in _EXCLUSIVE.get(ctype, ()) and c["type"] != ctype:
            if c["status"] == "True":
                c["status"] = "False"
                c["lastTransitionTime"] = now


def has_condition(status: dict[str, Any], ctype: str) -> bool:
    return any(c["type"] == ctype and c["status"] == "True"
               for c in status.get("conditions", ()))


def latest_condition(status: dict[str, Any]) -> str | None:
    conds = [c for c in status.get("conditions", ()) if c["status"] == "True"]
    if not conds:
        return None
    return max(conds, key=lambda c: c["lastTransitionTime"])["type"]


def is_finished(status: dict[str, Any]) -> bool:
    return any(has_condition(status, t) for t in _TERMINAL)
