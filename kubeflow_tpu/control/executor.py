"""Pod executor — the kubelet + container-runtime analog.

A Pod here is a unit of execution with two backends:

- `thread`: runs a registered Python callable in-process. This is the test
  and single-host path (the reference's fake-client trick taken one step
  further: the orchestration drives *real* work, SURVEY.md §7.0).
- `subprocess`: runs an argv with injected env — the real multi-process path
  (each JAX worker process gets its rendezvous env and calls
  jax.distributed.initialize, exactly how the reference's operators hand
  MASTER_ADDR to torch, §3.1).

Lifecycle written to status.phase: Pending → Scheduled (by the gang
scheduler) → Running → Succeeded | Failed{exitCode}. Deleting a Pod kills a
subprocess (SIGTERM→SIGKILL) and sets a cancel event for threads.
"""

from __future__ import annotations

import os
import re
import signal
import subprocess
import sys
import threading
import traceback
from typing import Any, Callable

from kubeflow_tpu.control.store import NotFoundError, ResourceStore

_TARGETS: dict[str, Callable[..., Any]] = {}


def _pod_log_re(namespace: str, pod: str) -> re.Pattern[str]:
    """Log files of exactly this pod: "{ns}.{pod}.{uid8}.log"."""
    return re.compile(
        rf"^{re.escape(namespace)}\.{re.escape(pod)}\.[0-9a-f]+\.log$")


def _job_log_re(namespace: str, job: str) -> re.Pattern[str]:
    """Log files of exactly this job's pods ("{ns}.{job}-{role}-{idx}.{uid8}
    .log"). The role-index tail is anchored so job "train" never matches
    files of job "train-v2"."""
    return re.compile(
        rf"^{re.escape(namespace)}\.{re.escape(job)}-[A-Za-z0-9]+-\d+\."
        rf"[0-9a-f]+\.log$")


def worker_target(name: str | None = None):
    """Register a callable as a thread-backend pod target.

    The callable receives (env: dict[str,str], cancel: threading.Event).
    Return value is ignored; raising marks the pod Failed (SystemExit(code)
    sets that exit code — how tests exercise retryable-exit-code policy).
    """
    def deco(fn):
        _TARGETS[name or fn.__name__] = fn
        return fn
    return deco


def get_target(name: str) -> Callable[..., Any]:
    """Resolve a pod target: registered name, built-in (lazily imported so
    the executor doesn't pull in jax), or dotted "pkg.mod:fn" path — the
    image-reference analog."""
    if name not in _TARGETS and ":" in name:
        import importlib
        mod, _, attr = name.partition(":")
        return getattr(importlib.import_module(mod), attr)
    if name not in _TARGETS:
        import importlib
        for builtin in ("kubeflow_tpu.training.job",
                        "kubeflow_tpu.rl.job"):
            importlib.import_module(builtin)
    return _TARGETS[name]


class _RunningPod:
    def __init__(self):
        self.cancel = threading.Event()
        self.proc: subprocess.Popen | None = None
        self.log_path: str | None = None
        self.log_buffer: list[str] = []


class _StdoutRouter:
    """Per-thread stdout routing so thread-backend pods get real log capture
    (the kubelet's container-stdout file analog). Installed lazily over
    sys.stdout; threads registered here write to their pod log file, all
    other threads pass through untouched.

    Limitation inherent to stdout proxying: a `contextlib.redirect_stdout`
    entered on another thread *before* a pod starts and exited *after* will
    restore the router with the redirect target still wrapped; pass-through
    output then goes to that target until the next install(). Closed-stream
    writes self-heal to the real stdout."""

    _installed: "_StdoutRouter | None" = None
    _install_lock = threading.Lock()

    def __init__(self, wrapped):
        self._wrapped = wrapped
        self._routes: dict[int, Any] = {}

    @classmethod
    def install(cls) -> "_StdoutRouter":
        with cls._install_lock:
            if cls._installed is None:
                cls._installed = cls(sys.stdout)
            # Something else (pytest capture, user code) may have replaced
            # sys.stdout since we last installed — rewrap the current one so
            # pass-through writes keep going to the active stdout.
            if sys.stdout is not cls._installed:
                cls._installed._wrapped = sys.stdout
                sys.stdout = cls._installed
            return cls._installed

    def register(self, fileobj) -> None:
        with self._install_lock:
            self._routes[threading.get_ident()] = fileobj

    def unregister(self) -> None:
        with self._install_lock:
            self._routes.pop(threading.get_ident(), None)

    def write(self, s: str) -> int:
        f = self._routes.get(threading.get_ident())
        if f is not None:
            f.write(s)
            f.flush()
            return len(s)
        try:
            return self._wrapped.write(s)
        except ValueError:
            # wrapped stream was closed underneath us (a capture/redirect
            # that ended after we rewrapped) — fall back to the real stdout
            self._wrapped = sys.__stdout__
            return self._wrapped.write(s)

    def flush(self) -> None:
        f = self._routes.get(threading.get_ident())
        try:
            (f or self._wrapped).flush()
        except ValueError:  # closed underlying stream
            pass

    def __getattr__(self, name):
        return getattr(self._wrapped, name)


class PodExecutor:
    def __init__(self, store: ResourceStore, log_dir: str | None = None):
        self.store = store
        self.log_dir = log_dir or os.path.join(
            os.environ.get("TMPDIR", "/tmp"), "kubeflow-tpu-logs")
        os.makedirs(self.log_dir, exist_ok=True)
        self._running: dict[str, _RunningPod] = {}
        self._lock = threading.Lock()
        self._watch = None
        self._stop = threading.Event()

    def start(self) -> None:
        self._watch = self.store.watch(kind="Pod")
        threading.Thread(target=self._watch_loop, daemon=True,
                         name="executor-watch").start()

    def stop(self) -> None:
        self._stop.set()
        if self._watch:
            self._watch.stop()
        with self._lock:
            running = list(self._running.values())
        for rp in running:
            self._kill(rp)

    # -- event handling ------------------------------------------------------

    def _watch_loop(self) -> None:
        for event, pod in self._watch:
            if self._stop.is_set():
                return
            uid = pod["metadata"]["uid"]
            if event == "DELETED":
                with self._lock:
                    rp = self._running.pop(uid, None)
                if rp:
                    self._kill(rp)
                continue
            if pod["status"].get("phase") != "Scheduled":
                continue
            with self._lock:
                if uid in self._running:
                    continue
                rp = _RunningPod()
                self._running[uid] = rp
            threading.Thread(target=self._run_pod, args=(pod, rp),
                             daemon=True,
                             name=f"pod-{pod['metadata']['name']}").start()

    # -- execution -----------------------------------------------------------

    def _set_phase(self, pod: dict[str, Any], phase: str, **extra) -> None:
        try:
            self.store.mutate(
                "Pod", pod["metadata"]["name"],
                lambda o: o["status"].update(phase=phase, **extra),
                pod["metadata"].get("namespace", "default"))
        except NotFoundError:
            pass  # pod deleted underneath us

    def _run_pod(self, pod: dict[str, Any], rp: _RunningPod) -> None:
        spec = pod["spec"]
        env = dict(spec.get("env", {}))
        env["KTPU_POD_NAME"] = pod["metadata"]["name"]
        env["KTPU_DEVICE_IDS"] = ",".join(
            str(d) for d in pod["status"].get("deviceIds", []))
        self._set_phase(pod, "Running")
        backend = spec.get("backend", "thread")
        try:
            if backend == "thread":
                exit_code = self._run_thread(pod, spec, env, rp)
            elif backend == "subprocess":
                exit_code = self._run_subprocess(pod, spec, env, rp)
            else:
                raise ValueError(f"unknown pod backend {backend!r}")
        except Exception:
            tb = traceback.format_exc()
            rp.log_buffer.append(tb)
            # the traceback must land on disk or it vanishes once the pod is
            # reaped — even when the log file was already opened (e.g. Popen
            # raised on a bad argv after _run_subprocess created the file)
            if not rp.log_path:
                rp.log_path = self._log_path(pod)
            with open(rp.log_path, "a", errors="replace") as f:
                f.write(tb)
            exit_code = 1
        finally:
            with self._lock:
                self._running.pop(pod["metadata"]["uid"], None)
        if rp.cancel.is_set() and exit_code != 0:
            # killed by deletion — phase written by deleter path; nothing to do
            return
        if exit_code == 0:
            self._set_phase(pod, "Succeeded", exitCode=0)
        else:
            self._set_phase(pod, "Failed", exitCode=exit_code)

    def _run_thread(self, pod, spec, env, rp: _RunningPod) -> int:
        fn = get_target(spec["target"])
        rp.log_path = self._log_path(pod)
        router = _StdoutRouter.install()
        with open(rp.log_path, "w", errors="replace") as logf:
            router.register(logf)
            try:
                fn(env, rp.cancel)
                return 0
            except SystemExit as e:
                return int(e.code or 0)
            except Exception:
                logf.write(traceback.format_exc())
                return 1
            finally:
                router.unregister()

    def _log_path(self, pod) -> str:
        return os.path.join(
            self.log_dir,
            f"{pod['metadata'].get('namespace', 'default')}."
            f"{pod['metadata']['name']}.{pod['metadata']['uid'][:8]}.log")

    def _run_subprocess(self, pod, spec, env, rp: _RunningPod) -> int:
        argv = spec.get("argv") or [sys.executable, "-c", spec["command"]]
        full_env = dict(os.environ)
        full_env.update(env)
        rp.log_path = self._log_path(pod)
        with open(rp.log_path, "wb") as logf:
            rp.proc = subprocess.Popen(
                argv, env=full_env, stdout=logf, stderr=subprocess.STDOUT,
                start_new_session=True)
            return rp.proc.wait()

    def _kill(self, rp: _RunningPod) -> None:
        rp.cancel.set()
        if rp.proc is not None and rp.proc.poll() is None:
            try:
                os.killpg(os.getpgid(rp.proc.pid), signal.SIGTERM)
                try:
                    rp.proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    os.killpg(os.getpgid(rp.proc.pid), signal.SIGKILL)
            except ProcessLookupError:
                pass

    # -- logs ----------------------------------------------------------------

    def logs(self, name: str, namespace: str = "default") -> str:
        """Best-effort pod logs (kubectl logs analog)."""
        pod = self.store.try_get("Pod", name, namespace)
        parts: list[str] = []
        if pod is not None:
            with self._lock:
                rp = self._running.get(pod["metadata"]["uid"])
            if rp is not None:
                parts.extend(rp.log_buffer)
                if rp.log_path and os.path.exists(rp.log_path):
                    with open(rp.log_path, "rb") as f:
                        parts.append(f.read().decode(errors="replace"))
                return "\n".join(parts)
        # finished/deleted: scan log dir for this exact pod's files; if
        # nothing matches, treat `name` as a job name and match its pods'
        # files ("{ns}.{job}-{role}-{idx}.{uid8}.log"). Anchored regexes —
        # a bare prefix would bleed job "train" into "train-v2" files.
        for pat in (_pod_log_re(namespace, name), _job_log_re(namespace, name)):
            for fn in sorted(os.listdir(self.log_dir)):
                if pat.match(fn):
                    with open(os.path.join(self.log_dir, fn), "rb") as f:
                        parts.append(f.read().decode(errors="replace"))
            if parts:
                break
        return "\n".join(parts)

    def job_log_files(self, job_name: str,
                      namespace: str = "default") -> dict[str, str]:
        """On-disk logs of a job's pods, keyed by pod name (files are named
        "{ns}.{pod}.{uid8}.log" and job pods are "{job}-{role}-{idx}")."""
        out: dict[str, str] = {}
        pat = _job_log_re(namespace, job_name)
        for fn in sorted(os.listdir(self.log_dir)):
            if pat.match(fn):
                pod_name = fn[len(f"{namespace}."):].rsplit(".", 2)[0]
                with open(os.path.join(self.log_dir, fn), "rb") as f:
                    out[pod_name] = f.read().decode(errors="replace")
        return out
