"""Reconciler base + Cluster wiring — controller-runtime's manager/workqueue
semantics (SURVEY.md §3.1) without Kubernetes.

Each Controller owns one primary kind. Watch events on the primary (and on
owned kinds, mapped back through ownerReferences) enqueue a namespaced key
into a deduplicating, rate-limited workqueue; a worker thread pops keys and
calls `reconcile(obj)`. Reconcile is level-triggered: it reads current state
from the store and drives it toward spec, returning an optional requeue
delay. Errors requeue with per-key exponential backoff.
"""

from __future__ import annotations

import heapq
import logging
import threading
import time
import traceback
from typing import Any

from kubeflow_tpu.control.expectations import Expectations
from kubeflow_tpu.control.store import ConflictError, ResourceStore
from kubeflow_tpu.utils.metrics import (RECONCILE_DURATION, RECONCILE_TOTAL,
                                        WORKQUEUE_DEPTH)

log = logging.getLogger("kubeflow_tpu.control")


class _RateLimitedQueue:
    """Deduplicating delay queue with per-key exponential failure backoff
    (workqueue.DefaultControllerRateLimiter analog: 5ms base, 30s cap here —
    our control loops run on second timescales, not minutes)."""

    BASE_DELAY = 0.005
    MAX_DELAY = 30.0

    def __init__(self):
        self._cv = threading.Condition()
        self._heap: list[tuple[float, str]] = []
        self._pending: set[str] = set()
        self._failures: dict[str, int] = {}
        self._shutdown = False

    def add(self, key: str, delay: float = 0.0) -> None:
        with self._cv:
            if key in self._pending:
                return
            self._pending.add(key)
            heapq.heappush(self._heap, (time.monotonic() + delay, key))
            self._cv.notify()

    def add_rate_limited(self, key: str) -> None:
        n = self._failures.get(key, 0)
        self._failures[key] = n + 1
        delay = min(self.BASE_DELAY * (2 ** n), self.MAX_DELAY)
        self.add(key, delay)

    def forget(self, key: str) -> None:
        self._failures.pop(key, None)

    def get(self, timeout: float | None = None) -> str | None:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while True:
                if self._shutdown:
                    return None
                now = time.monotonic()
                if self._heap and self._heap[0][0] <= now:
                    _, key = heapq.heappop(self._heap)
                    self._pending.discard(key)
                    return key
                wait = self._heap[0][0] - now if self._heap else timeout
                if deadline is not None:
                    wait = min(wait if wait is not None else 1e9,
                               deadline - now)
                    if wait <= 0:
                        return None
                self._cv.wait(wait)

    def depth(self) -> int:
        with self._cv:
            return len(self._pending)

    def shutdown(self) -> None:
        with self._cv:
            self._shutdown = True
            self._cv.notify_all()


class Controller:
    """Subclass and implement `reconcile(obj) -> requeue_after|None`."""

    kind: str = ""              # primary kind
    owned_kinds: tuple[str, ...] = ()  # secondary kinds mapped via ownerRefs
    resync_period: float = 2.0

    def __init__(self, cluster: "Cluster"):
        self.cluster = cluster
        self.store: ResourceStore = cluster.store
        self.expectations = Expectations()
        self.queue = _RateLimitedQueue()
        self._threads: list[threading.Thread] = []
        self._watches = []
        self._stop = threading.Event()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        for kind in (self.kind, *self.owned_kinds):
            w = self.store.watch(kind=kind)
            self._watches.append(w)
            t = threading.Thread(target=self._watch_loop, args=(w, kind),
                                 daemon=True, name=f"{self.kind}-watch-{kind}")
            t.start()
            self._threads.append(t)
        for name, target in [("worker", self._worker_loop),
                             ("resync", self._resync_loop)]:
            t = threading.Thread(target=target, daemon=True,
                                 name=f"{self.kind}-{name}")
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        self.queue.shutdown()
        for w in self._watches:
            w.stop()

    # -- event plumbing ------------------------------------------------------

    @staticmethod
    def key_of(obj: dict[str, Any]) -> str:
        return f"{obj['metadata'].get('namespace', 'default')}/{obj['metadata']['name']}"

    def _owner_key(self, obj: dict[str, Any]) -> str | None:
        for ref in obj["metadata"].get("ownerReferences", ()):
            if ref["kind"] == self.kind:
                ns = obj["metadata"].get("namespace", "default")
                return f"{ns}/{ref['name']}"
        return None

    def _watch_loop(self, w, kind: str) -> None:
        for event, obj in w:
            if self._stop.is_set():
                return
            if kind == self.kind:
                self.queue.add(self.key_of(obj))
            else:
                key = self._owner_key(obj)
                if key is None:
                    continue
                if event == "ADDED":
                    self.expectations.creation_observed(key)
                elif event == "DELETED":
                    self.expectations.deletion_observed(key)
                self.queue.add(key)

    def _resync_loop(self) -> None:
        while not self._stop.wait(self.resync_period):
            for obj in self.store.list(self.kind, namespace=None):
                self.queue.add(self.key_of(obj))

    def _worker_loop(self) -> None:
        while not self._stop.is_set():
            key = self.queue.get(timeout=1.0)
            WORKQUEUE_DEPTH.set(self.queue.depth(), kind=self.kind)
            if key is None:
                continue
            try:
                with RECONCILE_DURATION.time(kind=self.kind):
                    ns, name = key.split("/", 1)
                    obj = self.store.try_get(self.kind, name, ns)
                    requeue = (self.reconcile(obj) if obj is not None
                               else self.reconcile_deleted(name, ns))
                self.queue.forget(key)
                RECONCILE_TOTAL.inc(kind=self.kind, result="success")
                if requeue is not None:
                    self.queue.add(key, requeue)
            except ConflictError:
                RECONCILE_TOTAL.inc(kind=self.kind, result="conflict")
                self.queue.add_rate_limited(key)  # stale read; retry fast
            except Exception:
                RECONCILE_TOTAL.inc(kind=self.kind, result="error")
                log.error("reconcile %s %s failed:\n%s", self.kind, key,
                          traceback.format_exc())
                self.queue.add_rate_limited(key)

    # -- to implement --------------------------------------------------------

    def reconcile(self, obj: dict[str, Any]) -> float | None:
        raise NotImplementedError

    def reconcile_deleted(self, name: str, namespace: str) -> float | None:
        """Hook for controllers holding out-of-store resources (servers,
        sockets) — the finalizer analog. Default: nothing to clean."""
        return None


class Cluster:
    """The single-process "cluster": store + scheduler + executor + the
    controller set, started/stopped together (the manager analog).

    Usage:
        cluster = Cluster()
        cluster.add(JAXJobController)
        cluster.start()
        cluster.store.create(job)
        ...
        cluster.stop()
    """

    def __init__(self, n_devices: int | None = None, packing=None):
        # local imports: scheduler/executor import back into this package
        from kubeflow_tpu.control.executor import PodExecutor
        from kubeflow_tpu.control.scheduler import (DeviceInventory,
                                                    GangScheduler)

        self.store = ResourceStore()
        # `packing`: an optional scheduler.PackingPolicy — chips stay
        # exclusive without one (see DeviceInventory)
        self.inventory = DeviceInventory(n_devices=n_devices,
                                         packing=packing)
        self.scheduler = GangScheduler(self.store, self.inventory)
        self.executor = PodExecutor(self.store)
        self.controllers: list[Controller] = []

    def add(self, controller_cls: type[Controller], **kwargs) -> Controller:
        c = controller_cls(self, **kwargs)
        self.controllers.append(c)
        return c

    def start(self) -> None:
        self.scheduler.start()
        self.executor.start()
        for c in self.controllers:
            c.start()

    def stop(self) -> None:
        for c in self.controllers:
            c.stop()
        self.executor.stop()
        self.scheduler.stop()
        self.store.stop_watches()

    def __enter__(self) -> "Cluster":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def wait_for(self, kind: str, name: str, predicate,
                 namespace: str = "default", timeout: float = 60.0,
                 poll: float = 0.05) -> dict[str, Any]:
        """Poll until predicate(obj) — the SDK's wait_for_job_conditions
        analog; raises TimeoutError with the last status for debuggability."""
        deadline = time.monotonic() + timeout
        obj = None
        while time.monotonic() < deadline:
            obj = self.store.try_get(kind, name, namespace)
            if obj is not None and predicate(obj):
                return obj
            time.sleep(poll)
        raise TimeoutError(
            f"{kind}/{name}: predicate not met in {timeout}s; "
            f"last status={None if obj is None else obj.get('status')}")
