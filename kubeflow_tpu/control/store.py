"""CRD-shaped resource store: the kube-apiserver + etcd analog.

Objects keep the familiar shape (`apiVersion`/`kind`/`metadata`/`spec`/
`status`) so YAML specs written for the reference's CRDs translate 1:1, and a
future bridge onto a real cluster stays possible (SURVEY.md §5.6). Semantics
mirrored from the k8s API machinery the reference's controllers rely on:

- monotonically increasing `resourceVersion`, optimistic-concurrency updates
  (stale writes raise ConflictError — the reconciler then re-reads + retries);
- label selectors on list;
- watch streams (ADDED/MODIFIED/DELETED events) feeding controller workqueues;
- delete is immediate (no finalizers — nothing holds external resources here
  that the owning controller doesn't clean up itself via ownerReferences).
"""

from __future__ import annotations

import copy
import itertools
import queue
import threading
import uuid
import time
from typing import Any, Callable, Iterator


class StoreError(Exception):
    pass


class ConflictError(StoreError):
    """resourceVersion mismatch on update."""


class NotFoundError(StoreError):
    pass


class AlreadyExistsError(StoreError):
    pass


def new_resource(kind: str, name: str, spec: dict[str, Any] | None = None, *,
                 namespace: str = "default",
                 labels: dict[str, str] | None = None,
                 owner: dict[str, Any] | None = None,
                 api_version: str = "kubeflow-tpu/v1") -> dict[str, Any]:
    """Build an object in CRD shape. `owner` is an owning object whose
    metadata we link via ownerReferences (garbage-collection analog)."""
    meta: dict[str, Any] = {
        "name": name,
        "namespace": namespace,
        "labels": dict(labels or {}),
    }
    if owner is not None:
        meta["ownerReferences"] = [{
            "kind": owner["kind"],
            "name": owner["metadata"]["name"],
            "uid": owner["metadata"]["uid"],
        }]
    return {
        "apiVersion": api_version,
        "kind": kind,
        "metadata": meta,
        "spec": copy.deepcopy(spec or {}),
        "status": {},
    }


def obj_key(obj: dict[str, Any]) -> tuple[str, str, str]:
    return (obj["kind"], obj["metadata"].get("namespace", "default"),
            obj["metadata"]["name"])


class _Watch:
    """One watch stream; events are queued so slow consumers can't block
    writers (the informer-cache property controllers depend on)."""

    def __init__(self, kind: str | None, namespace: str | None):
        self.kind = kind
        self.namespace = namespace
        self.events: queue.Queue = queue.Queue()
        self.closed = False

    def matches(self, obj: dict[str, Any]) -> bool:
        if self.kind is not None and obj["kind"] != self.kind:
            return False
        if (self.namespace is not None
                and obj["metadata"].get("namespace") != self.namespace):
            return False
        return True

    def stop(self) -> None:
        self.closed = True
        self.events.put(None)

    def __iter__(self) -> Iterator[tuple[str, dict[str, Any]]]:
        while True:
            ev = self.events.get()
            if ev is None or self.closed:
                return
            yield ev


class ResourceStore:
    """Thread-safe versioned object store with watches."""

    def __init__(self):
        self._lock = threading.RLock()
        self._objects: dict[tuple[str, str, str], dict[str, Any]] = {}
        self._rv = itertools.count(1)
        self._watches: list[_Watch] = []
        self._mutating_hooks: dict[str, list] = {}

    # -- admission (mutating-webhook analog, SURVEY.md §2.1) ------------------

    def add_mutating_hook(self, kind: str, fn) -> None:
        """Register fn(store, obj) -> None, called on every create() of
        `kind` before the object is persisted — the admission-webhook
        injection point (PodDefaults etc.). Hooks mutate obj in place."""
        self._mutating_hooks.setdefault(kind, []).append(fn)

    # -- CRUD ----------------------------------------------------------------

    def create(self, obj: dict[str, Any]) -> dict[str, Any]:
        with self._lock:
            key = obj_key(obj)
            if key in self._objects:
                raise AlreadyExistsError(f"{key} already exists")
            obj = copy.deepcopy(obj)
            for hook in self._mutating_hooks.get(obj["kind"], ()):
                hook(self, obj)
            meta = obj["metadata"]
            meta.setdefault("namespace", "default")
            meta["uid"] = uuid.uuid4().hex
            meta["resourceVersion"] = next(self._rv)
            meta["creationTimestamp"] = time.time()
            obj.setdefault("status", {})
            self._objects[key] = obj
            self._notify("ADDED", obj)
            return copy.deepcopy(obj)

    def get(self, kind: str, name: str, namespace: str = "default"
            ) -> dict[str, Any]:
        with self._lock:
            obj = self._objects.get((kind, namespace, name))
            if obj is None:
                raise NotFoundError(f"{kind}/{namespace}/{name}")
            return copy.deepcopy(obj)

    def try_get(self, kind: str, name: str, namespace: str = "default"
                ) -> dict[str, Any] | None:
        try:
            return self.get(kind, name, namespace)
        except NotFoundError:
            return None

    def list(self, kind: str, namespace: str | None = "default",
             labels: dict[str, str] | None = None) -> list[dict[str, Any]]:
        """namespace=None lists across all namespaces."""
        with self._lock:
            out = []
            for (k, ns, _), obj in self._objects.items():
                if k != kind:
                    continue
                if namespace is not None and ns != namespace:
                    continue
                if labels and any(
                        obj["metadata"]["labels"].get(lk) != lv
                        for lk, lv in labels.items()):
                    continue
                out.append(copy.deepcopy(obj))
            out.sort(key=lambda o: o["metadata"]["resourceVersion"])
            return out

    def update(self, obj: dict[str, Any]) -> dict[str, Any]:
        """Full-object update with optimistic concurrency."""
        with self._lock:
            key = obj_key(obj)
            cur = self._objects.get(key)
            if cur is None:
                raise NotFoundError(f"{key}")
            if (obj["metadata"].get("resourceVersion")
                    != cur["metadata"]["resourceVersion"]):
                raise ConflictError(
                    f"{key}: stale resourceVersion "
                    f"{obj['metadata'].get('resourceVersion')} != "
                    f"{cur['metadata']['resourceVersion']}")
            obj = copy.deepcopy(obj)
            obj["metadata"]["resourceVersion"] = next(self._rv)
            self._objects[key] = obj
            self._notify("MODIFIED", obj)
            return copy.deepcopy(obj)

    def mutate(self, kind: str, name: str,
               fn: Callable[[dict[str, Any]], None],
               namespace: str = "default") -> dict[str, Any]:
        """Read-modify-write under the store lock — the retry-on-conflict
        helper every reconciler status write goes through."""
        with self._lock:
            obj = self.get(kind, name, namespace)
            fn(obj)
            return self.update(obj)

    def delete(self, kind: str, name: str, namespace: str = "default") -> None:
        with self._lock:
            key = (kind, namespace, name)
            obj = self._objects.pop(key, None)
            if obj is None:
                raise NotFoundError(f"{key}")
            self._notify("DELETED", obj)

    def try_delete(self, kind: str, name: str,
                   namespace: str = "default") -> bool:
        try:
            self.delete(kind, name, namespace)
            return True
        except NotFoundError:
            return False

    def delete_owned_by(self, owner: dict[str, Any]) -> int:
        """Garbage collection: remove everything ownerReference'd to `owner`
        (the k8s GC-controller analog, run synchronously by the owner's
        reconciler on delete/TTL)."""
        uid = owner["metadata"]["uid"]
        with self._lock:
            doomed = [
                obj_key(o) for o in self._objects.values()
                if any(r.get("uid") == uid
                       for r in o["metadata"].get("ownerReferences", ()))
            ]
            for kind, ns, name in doomed:
                self.delete(kind, name, ns)
            return len(doomed)

    # -- watch ---------------------------------------------------------------

    def watch(self, kind: str | None = None,
              namespace: str | None = None) -> _Watch:
        w = _Watch(kind, namespace)
        with self._lock:
            self._watches.append(w)
        return w

    def stop_watches(self) -> None:
        with self._lock:
            for w in self._watches:
                w.stop()
            self._watches.clear()

    def _notify(self, event: str, obj: dict[str, Any]) -> None:
        for w in self._watches:
            if not w.closed and w.matches(obj):
                w.events.put((event, copy.deepcopy(obj)))
