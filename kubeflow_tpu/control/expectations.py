"""Controller expectations cache — the informer-race defense the reference's
shared job framework is built on (SURVEY.md §5.2, `common/expectation.go`).

The race it closes: a reconciler creates 4 pods, but its watch cache hasn't
seen them yet; the next reconcile would count 0 observed pods and create 4
more. Before acting, the reconciler records "I expect +4 creations"; watch
events decrement the counter; until it reaches zero (or times out) the
reconciler treats its view as stale and only updates status, never creates
or deletes.
"""

from __future__ import annotations

import threading
import time


_TIMEOUT_S = 5 * 60.0  # expectations expire — controller self-heals if events
                       # were lost (same 5min as the reference)


class Expectations:
    def __init__(self):
        self._lock = threading.Lock()
        # key -> [adds_pending, dels_pending, set_time]
        self._exp: dict[str, list[float]] = {}

    def expect_creations(self, key: str, n: int) -> None:
        with self._lock:
            e = self._exp.setdefault(key, [0, 0, time.monotonic()])
            e[0] += n
            e[2] = time.monotonic()

    def expect_deletions(self, key: str, n: int) -> None:
        with self._lock:
            e = self._exp.setdefault(key, [0, 0, time.monotonic()])
            e[1] += n
            e[2] = time.monotonic()

    def creation_observed(self, key: str) -> None:
        with self._lock:
            e = self._exp.get(key)
            if e and e[0] > 0:
                e[0] -= 1

    def deletion_observed(self, key: str) -> None:
        with self._lock:
            e = self._exp.get(key)
            if e and e[1] > 0:
                e[1] -= 1

    def satisfied(self, key: str) -> bool:
        with self._lock:
            e = self._exp.get(key)
            if e is None:
                return True
            if e[0] <= 0 and e[1] <= 0:
                return True
            return time.monotonic() - e[2] > _TIMEOUT_S

    def forget(self, key: str) -> None:
        with self._lock:
            self._exp.pop(key, None)
