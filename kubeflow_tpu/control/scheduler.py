"""Gang scheduler + device inventory — the Volcano PodGroup / kube-scheduler
analog (SURVEY.md §2.2 "Gang scheduling", §5.3).

The reference creates a PodGroup sized minAvailable=Σreplicas so a distributed
job is placed all-or-nothing — partial placement deadlocks NCCL rendezvous.
The same hazard exists here (jax.distributed.initialize blocks until all
processes arrive), so the semantics carry over: pods carrying a `pod-group`
label are only bound when the whole group fits the device inventory.

The inventory models one TPU slice: `tpu` chips are countable, exclusive
resources (the `google.com/tpu` extended-resource analog); `cpu` is a soft
resource. Binding records concrete chip ids in `status.deviceIds` so a worker
can pin itself (JAX visible-devices) — the device-plugin mount analog.

Concurrency packing (PAPERS.md "Exploring the limits of Concurrency in ML
Training on Google TPUs", ROADMAP #5): exclusive chips are the safe default,
but a chip that is not roofline-bound on one workload can run a second in
the gaps. A pod that declares `resources: {tpu: 1, packing_class: "<class>"}`
opts into sharing; the inventory co-locates it onto an occupied chip ONLY
when a `PackingPolicy` — fed by measured solo-vs-packed interference records
(kubeflow_tpu.rl.packing) — has admitted that class pair. No policy, or no
admitted pair, degrades to the exclusive behavior.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Iterable

from kubeflow_tpu.control.store import ResourceStore

GROUP_LABEL = "kubeflow-tpu/pod-group"

#: pod spec.resources key that opts a single-chip pod into packing
PACKING_CLASS_KEY = "packing_class"


@dataclasses.dataclass(frozen=True)
class PackingDecision:
    allow: bool
    reason: str
    combined_retention: float | None = None

    def to_json(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


class PackingPolicy:
    """Chip-time-slicing/packing policy, taught by interference records.

    The decision quantity is `combined_retention` = packed_a/solo_a +
    packed_b/solo_b. Perfect time-slicing scores exactly 1.0 (each
    workload owns the chip half the time), so a pair is admitted only
    when the measured sum clears `min_combined_retention` AND neither
    side is starved below `min_each_retention` (the SLO guard: a packing
    win that zeroes one tenant's throughput is not a win).

    `learn(class_a, class_b, record)` applies `decide` and remembers the
    verdict; `allows(cls, existing)` is the inventory-facing query.
    """

    def __init__(self, *, min_combined_retention: float = 1.05,
                 min_each_retention: float = 0.25, max_per_chip: int = 2):
        if max_per_chip < 1:
            raise ValueError("max_per_chip must be >= 1")
        self.min_combined_retention = min_combined_retention
        self.min_each_retention = min_each_retention
        self.max_per_chip = max_per_chip
        self._pairs: dict[frozenset[str], PackingDecision] = {}

    def decide(self, record: dict[str, Any]) -> PackingDecision:
        """Pure decision logic over a record with solo_a/solo_b/packed_a/
        packed_b (an InterferenceRecord.to_json shape)."""
        solo_a, solo_b = record.get("solo_a", 0), record.get("solo_b", 0)
        if solo_a <= 0 or solo_b <= 0:
            return PackingDecision(False, "unmeasured solo rate")
        ra = record.get("packed_a", 0) / solo_a
        rb = record.get("packed_b", 0) / solo_b
        combined = ra + rb
        if min(ra, rb) < self.min_each_retention:
            return PackingDecision(
                False, f"one workload starved: retention "
                f"{min(ra, rb):.3f} < {self.min_each_retention}", combined)
        if combined < self.min_combined_retention:
            return PackingDecision(
                False, f"time-slicing wins: combined retention "
                f"{combined:.3f} < {self.min_combined_retention}", combined)
        return PackingDecision(
            True, f"packing beats time-slicing: combined retention "
            f"{combined:.3f}", combined)

    def learn(self, class_a: str, class_b: str,
              record: dict[str, Any]) -> PackingDecision:
        d = self.decide(record)
        self._pairs[frozenset((class_a, class_b))] = d
        return d

    def allows(self, cls: str, existing: Iterable[str]) -> bool:
        """May a pod of `cls` join a chip already running `existing`?"""
        occupants = list(existing)
        if len(occupants) + 1 > self.max_per_chip:
            return False
        for other in occupants:
            d = self._pairs.get(frozenset((cls, other)))
            if d is None or not d.allow:
                return False
        return True

    def to_json(self) -> dict[str, Any]:
        return {
            "min_combined_retention": self.min_combined_retention,
            "min_each_retention": self.min_each_retention,
            "max_per_chip": self.max_per_chip,
            "pairs": {"|".join(sorted(k)): d.to_json()
                      for k, d in self._pairs.items()},
        }


class DeviceInventory:
    """Countable chip inventory: exclusive allocation by default, policy-
    gated chip sharing for pods that declare a packing class."""

    def __init__(self, n_devices: int | None = None, cpu_capacity: int = 256,
                 packing: PackingPolicy | None = None):
        if n_devices is None:
            n_devices = 8
        self.n_devices = n_devices
        self.cpu_capacity = cpu_capacity
        self.packing = packing
        self._lock = threading.Lock()
        self._free = set(range(n_devices))
        self._cpu_used = 0
        self._held: dict[str, tuple[list[int], int]] = {}  # uid -> (chips, cpu)
        # chips occupied by packable pods: chip -> [(uid, class), ...]
        self._shared: dict[int, list[tuple[str, str]]] = {}

    def set_packing(self, policy: PackingPolicy | None) -> None:
        """Install/replace the packing policy (already-bound pods keep
        their chips; only future placement consults the new policy)."""
        with self._lock:
            self.packing = policy

    def _place(self, request: dict[str, Any], free: set[int],
               shared: dict[int, list[str]]
               ) -> tuple[list[int] | None, str | None]:
        """THE greedy placement step, shared by fits() and allocate() so
        the gang gate and the per-pod bind can never disagree: a
        packable single-chip request joins the lowest-id compatible
        shared chip, else opens `min(free)` as a new shared chip; an
        exclusive request takes the lowest free ids. Mutates the passed
        views and returns (chips, packing_class) — allocate passes live
        state (a class-only shadow of _shared), fits passes copies."""
        tpu = request.get("tpu", 0)
        cls = request.get(PACKING_CLASS_KEY)
        if tpu == 1 and cls is not None and self.packing is not None:
            chip = next((ch for ch in sorted(shared)
                         if self.packing.allows(cls, shared[ch])), None)
            if chip is None:
                if not free:
                    return None, cls
                chip = min(free)
                free.discard(chip)
                shared[chip] = []
            shared[chip].append(cls)
            return [chip], cls
        if tpu > len(free):
            return None, None
        chips = sorted(free)[:tpu]
        free -= set(chips)
        return chips, None

    def _shared_classes(self) -> dict[int, list[str]]:
        return {chip: [c for _, c in occs]
                for chip, occs in self._shared.items()}

    def fits(self, requests: list[dict[str, Any]]) -> bool:
        """Dry-run placement of a whole gang through the same _place
        step the binds will take, against copied views."""
        with self._lock:
            cpu = sum(r.get("cpu", 1) for r in requests)
            if self._cpu_used + cpu > self.cpu_capacity:
                return False
            free = set(self._free)
            shared = self._shared_classes()
            return all(self._place(r, free, shared)[0] is not None
                       for r in requests)

    def allocate(self, uid: str, request: dict[str, Any]) -> list[int] | None:
        with self._lock:
            cpu = request.get("cpu", 1)
            if self._cpu_used + cpu > self.cpu_capacity:
                return None
            free = set(self._free)
            chips, cls = self._place(request, free, self._shared_classes())
            if chips is None:
                return None
            self._free = free
            if cls is not None:
                # single shared chip: record the occupant (opening the
                # chip if _place just took it out of the free set)
                self._shared.setdefault(chips[0], []).append((uid, cls))
            self._cpu_used += cpu
            self._held[uid] = (chips, cpu)
            return chips

    def release(self, uid: str) -> None:
        with self._lock:
            held = self._held.pop(uid, None)
            if not held:
                return
            self._cpu_used -= held[1]
            for chip in held[0]:
                occs = self._shared.get(chip)
                if occs is not None:
                    self._shared[chip] = [
                        (u, c) for u, c in occs if u != uid]
                    if not self._shared[chip]:
                        del self._shared[chip]
                        self._free.add(chip)
                else:
                    self._free.add(chip)

    def usage(self) -> dict[str, int]:
        with self._lock:
            return {"tpu_used": self.n_devices - len(self._free),
                    "tpu_capacity": self.n_devices,
                    "tpu_shared": len(self._shared),
                    "cpu_used": self._cpu_used}


class GangScheduler:
    """Binds Pending pods: grouped pods all-or-nothing, others immediately."""

    def __init__(self, store: ResourceStore, inventory: DeviceInventory):
        self.store = store
        self.inventory = inventory
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: threading.Thread | None = None
        self._watch = None

    def start(self) -> None:
        self._watch = self.store.watch(kind="Pod")
        threading.Thread(target=self._watch_loop, daemon=True,
                         name="sched-watch").start()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="scheduler")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._watch:
            self._watch.stop()

    def _watch_loop(self) -> None:
        for event, obj in self._watch:
            if self._stop.is_set():
                return
            if event == "DELETED" or obj["status"].get("phase") in (
                    "Succeeded", "Failed"):
                self.inventory.release(obj["metadata"]["uid"])
            self._wake.set()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(timeout=0.5)
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                self._schedule_round()
            except Exception:  # scheduler must never die
                import traceback
                traceback.print_exc()

    def _schedule_round(self) -> None:
        pending = [p for p in self.store.list("Pod", namespace=None)
                   if p["status"].get("phase", "Pending") == "Pending"]
        groups: dict[str, list[dict[str, Any]]] = {}
        singles: list[dict[str, Any]] = []
        for p in pending:
            g = p["metadata"]["labels"].get(GROUP_LABEL)
            (groups.setdefault(g, []) if g else singles).append(p)

        for pod in singles:
            self._bind_if_fits([pod])

        for gname, pods in groups.items():
            ns = pods[0]["metadata"].get("namespace", "default")
            pg = self.store.try_get("PodGroup", gname, ns)
            min_avail = (pg["spec"].get("minAvailable", len(pods))
                         if pg else len(pods))
            # Count already-placed members toward the gang — including
            # Succeeded ones: a member that already ran to completion was
            # certainly placed, and excluding it deadlocks gangs whose fast
            # members finish before the slow ones are even created.
            bound = [p for p in self.store.list("Pod", ns,
                                                labels={GROUP_LABEL: gname})
                     if p["status"].get("phase") not in ("Pending", "Failed",
                                                         None)]
            if len(pods) + len(bound) < min_avail:
                self._mark_unschedulable(pods, "WaitingForGang")
                continue
            if not self.inventory.fits(
                    [p["spec"].get("resources", {}) for p in pods]):
                self._mark_unschedulable(pods, "InsufficientDevices")
                continue
            self._bind_if_fits(pods)

    def _bind_if_fits(self, pods: list[dict[str, Any]]) -> None:
        allocated: list[dict[str, Any]] = []
        for pod in pods:
            chips = self.inventory.allocate(
                pod["metadata"]["uid"], pod["spec"].get("resources", {}))
            if chips is None:
                for done in allocated:  # partial gang — roll back
                    self.inventory.release(done["metadata"]["uid"])
                self._mark_unschedulable(pods, "InsufficientDevices")
                return
            allocated.append(pod)
            pod["_chips"] = chips
        for pod in pods:
            chips = pod.pop("_chips")
            try:
                self.store.mutate(
                    "Pod", pod["metadata"]["name"],
                    lambda o, c=chips: o["status"].update(
                        phase="Scheduled", deviceIds=c),
                    pod["metadata"].get("namespace", "default"))
            except Exception:
                self.inventory.release(pod["metadata"]["uid"])

    def _mark_unschedulable(self, pods: list[dict[str, Any]],
                            reason: str) -> None:
        for pod in pods:
            if pod["status"].get("reason") == reason:
                continue
            try:
                self.store.mutate(
                    "Pod", pod["metadata"]["name"],
                    lambda o: o["status"].update(reason=reason),
                    pod["metadata"].get("namespace", "default"))
            except Exception:
                pass
