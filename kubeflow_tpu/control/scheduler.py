"""Gang scheduler + device inventory — the Volcano PodGroup / kube-scheduler
analog (SURVEY.md §2.2 "Gang scheduling", §5.3).

The reference creates a PodGroup sized minAvailable=Σreplicas so a distributed
job is placed all-or-nothing — partial placement deadlocks NCCL rendezvous.
The same hazard exists here (jax.distributed.initialize blocks until all
processes arrive), so the semantics carry over: pods carrying a `pod-group`
label are only bound when the whole group fits the device inventory.

The inventory models one TPU slice: `tpu` chips are countable, exclusive
resources (the `google.com/tpu` extended-resource analog); `cpu` is a soft
resource. Binding records concrete chip ids in `status.deviceIds` so a worker
can pin itself (JAX visible-devices) — the device-plugin mount analog.
"""

from __future__ import annotations

import threading
from typing import Any

from kubeflow_tpu.control.store import ResourceStore

GROUP_LABEL = "kubeflow-tpu/pod-group"


class DeviceInventory:
    """Countable chip inventory with exclusive allocation."""

    def __init__(self, n_devices: int | None = None, cpu_capacity: int = 256):
        if n_devices is None:
            n_devices = 8
        self.n_devices = n_devices
        self.cpu_capacity = cpu_capacity
        self._lock = threading.Lock()
        self._free = set(range(n_devices))
        self._cpu_used = 0
        self._held: dict[str, tuple[list[int], int]] = {}  # uid -> (chips, cpu)

    def fits(self, requests: list[dict[str, int]]) -> bool:
        with self._lock:
            tpu = sum(r.get("tpu", 0) for r in requests)
            cpu = sum(r.get("cpu", 1) for r in requests)
            return (tpu <= len(self._free)
                    and self._cpu_used + cpu <= self.cpu_capacity)

    def allocate(self, uid: str, request: dict[str, int]) -> list[int] | None:
        with self._lock:
            tpu = request.get("tpu", 0)
            cpu = request.get("cpu", 1)
            if tpu > len(self._free) or self._cpu_used + cpu > self.cpu_capacity:
                return None
            chips = sorted(self._free)[:tpu]
            self._free -= set(chips)
            self._cpu_used += cpu
            self._held[uid] = (chips, cpu)
            return chips

    def release(self, uid: str) -> None:
        with self._lock:
            held = self._held.pop(uid, None)
            if held:
                self._free |= set(held[0])
                self._cpu_used -= held[1]

    def usage(self) -> dict[str, int]:
        with self._lock:
            return {"tpu_used": self.n_devices - len(self._free),
                    "tpu_capacity": self.n_devices,
                    "cpu_used": self._cpu_used}


class GangScheduler:
    """Binds Pending pods: grouped pods all-or-nothing, others immediately."""

    def __init__(self, store: ResourceStore, inventory: DeviceInventory):
        self.store = store
        self.inventory = inventory
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: threading.Thread | None = None
        self._watch = None

    def start(self) -> None:
        self._watch = self.store.watch(kind="Pod")
        threading.Thread(target=self._watch_loop, daemon=True,
                         name="sched-watch").start()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="scheduler")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._watch:
            self._watch.stop()

    def _watch_loop(self) -> None:
        for event, obj in self._watch:
            if self._stop.is_set():
                return
            if event == "DELETED" or obj["status"].get("phase") in (
                    "Succeeded", "Failed"):
                self.inventory.release(obj["metadata"]["uid"])
            self._wake.set()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(timeout=0.5)
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                self._schedule_round()
            except Exception:  # scheduler must never die
                import traceback
                traceback.print_exc()

    def _schedule_round(self) -> None:
        pending = [p for p in self.store.list("Pod", namespace=None)
                   if p["status"].get("phase", "Pending") == "Pending"]
        groups: dict[str, list[dict[str, Any]]] = {}
        singles: list[dict[str, Any]] = []
        for p in pending:
            g = p["metadata"]["labels"].get(GROUP_LABEL)
            (groups.setdefault(g, []) if g else singles).append(p)

        for pod in singles:
            self._bind_if_fits([pod])

        for gname, pods in groups.items():
            ns = pods[0]["metadata"].get("namespace", "default")
            pg = self.store.try_get("PodGroup", gname, ns)
            min_avail = (pg["spec"].get("minAvailable", len(pods))
                         if pg else len(pods))
            # Count already-placed members toward the gang — including
            # Succeeded ones: a member that already ran to completion was
            # certainly placed, and excluding it deadlocks gangs whose fast
            # members finish before the slow ones are even created.
            bound = [p for p in self.store.list("Pod", ns,
                                                labels={GROUP_LABEL: gname})
                     if p["status"].get("phase") not in ("Pending", "Failed",
                                                         None)]
            if len(pods) + len(bound) < min_avail:
                self._mark_unschedulable(pods, "WaitingForGang")
                continue
            if not self.inventory.fits(
                    [p["spec"].get("resources", {}) for p in pods]):
                self._mark_unschedulable(pods, "InsufficientDevices")
                continue
            self._bind_if_fits(pods)

    def _bind_if_fits(self, pods: list[dict[str, Any]]) -> None:
        allocated: list[dict[str, Any]] = []
        for pod in pods:
            chips = self.inventory.allocate(
                pod["metadata"]["uid"], pod["spec"].get("resources", {}))
            if chips is None:
                for done in allocated:  # partial gang — roll back
                    self.inventory.release(done["metadata"]["uid"])
                self._mark_unschedulable(pods, "InsufficientDevices")
                return
            allocated.append(pod)
            pod["_chips"] = chips
        for pod in pods:
            chips = pod.pop("_chips")
            try:
                self.store.mutate(
                    "Pod", pod["metadata"]["name"],
                    lambda o, c=chips: o["status"].update(
                        phase="Scheduled", deviceIds=c),
                    pod["metadata"].get("namespace", "default"))
            except Exception:
                self.inventory.release(pod["metadata"]["uid"])

    def _mark_unschedulable(self, pods: list[dict[str, Any]],
                            reason: str) -> None:
        for pod in pods:
            if pod["status"].get("reason") == reason:
                continue
            try:
                self.store.mutate(
                    "Pod", pod["metadata"]["name"],
                    lambda o: o["status"].update(reason=reason),
                    pod["metadata"].get("namespace", "default"))
            except Exception:
                pass
