"""L5 control plane: CRD-shaped resource store + reconcilers.

The reference platform is an orchestrator of Kubernetes custom resources
(SURVEY.md §1): an apiserver stores typed objects, controllers watch them and
reconcile desired vs actual state. This package reimplements those semantics
natively — no kubectl, no etcd — around TPU training processes:

- store.py       : the apiserver analog (versioned objects, watches)
- conditions.py  : JobCondition status machinery (kubeflow/common analog)
- expectations.py: in-flight create/delete tracking (informer-race defense)
- controller.py  : reconciler base (workqueue, resync, rate limiting)
- scheduler.py   : gang scheduler + device inventory (Volcano PodGroup analog)
- executor.py    : pod runtime (thread/subprocess backends — the kubelet analog)
- jobs.py        : JAXJob controller (training-operator analog)
- frameworks.py  : TFJob/PyTorchJob/XGBoostJob/MXJob/PaddleJob/MPIJob kinds
                   on the same engine (per-kind SetClusterSpec env analogs)
"""

from kubeflow_tpu.control.store import (  # noqa: F401
    ResourceStore,
    ConflictError,
    NotFoundError,
    AlreadyExistsError,
    new_resource,
)
from kubeflow_tpu.control.conditions import (  # noqa: F401
    JobConditionType,
    set_condition,
    has_condition,
    is_finished,
)
from kubeflow_tpu.control.controller import Controller, Cluster  # noqa: F401
from kubeflow_tpu.control.scheduler import (  # noqa: F401
    DeviceInventory,
    GangScheduler,
    PackingDecision,
    PackingPolicy,
)
from kubeflow_tpu.control.executor import PodExecutor, worker_target  # noqa: F401
from kubeflow_tpu.control.jobs import JAXJobController  # noqa: F401
from kubeflow_tpu.control.frameworks import (  # noqa: F401
    TRAINING_CONTROLLERS,
    FRAMEWORK_KINDS,
    TFJobController,
    PyTorchJobController,
    XGBoostJobController,
    MXJobController,
    PaddleJobController,
    MPIJobController,
    add_training_controllers,
)
