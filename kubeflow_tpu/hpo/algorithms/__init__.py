"""Suggestion algorithms (Katib suggestion-service analog, SURVEY.md §2.3).

Importing this package registers: random, grid, sobol/quasirandom, hyperband,
tpe, bayesianoptimization (alias: bayesian), cmaes, pbt, enas.
"""

from kubeflow_tpu.hpo.algorithms.base import (Algorithm, TrialResult,
                                              algorithm_names, make_algorithm,
                                              register)
from kubeflow_tpu.hpo.algorithms import basic as _basic          # noqa: F401
from kubeflow_tpu.hpo.algorithms import tpe as _tpe              # noqa: F401
from kubeflow_tpu.hpo.algorithms import bayesian as _bayesian    # noqa: F401
from kubeflow_tpu.hpo.algorithms import cmaes as _cmaes          # noqa: F401
from kubeflow_tpu.hpo.algorithms import pbt as _pbt              # noqa: F401
from kubeflow_tpu.hpo.algorithms import enas as _enas            # noqa: F401

__all__ = ["Algorithm", "TrialResult", "algorithm_names", "make_algorithm",
           "register"]
