"""Gaussian-process Bayesian optimization — Katib's `bayesianoptimization`
(⊘ katib pkg/suggestion/v1beta1/skopt; GP + Expected Improvement).

Pure numpy: Matérn-5/2 kernel on the unit cube, Cholesky GP posterior,
EI acquisition maximized over a quasirandom candidate sweep plus local
perturbations of the incumbent. O(n³) in observed trials — fine for the
hundreds-of-trials regime HPO sweeps live in.
"""

from __future__ import annotations

import numpy as np

from kubeflow_tpu.hpo.algorithms.base import Algorithm, register


def _matern52(a: np.ndarray, b: np.ndarray, ls: float) -> np.ndarray:
    d = np.sqrt(((a[:, None, :] - b[None, :, :]) ** 2).sum(-1) + 1e-12) / ls
    s = np.sqrt(5.0) * d
    return (1.0 + s + s * s / 3.0) * np.exp(-s)


@register("bayesianoptimization")
@register("bayesian")
# both names resolve: "bayesianoptimization" is Katib's canonical id; the
# short alias is what examples/katib-experiment.yaml (and humans) write
class BayesianOptimization(Algorithm):
    def __init__(self, space, settings=None, seed=0):
        super().__init__(space, settings, seed)
        self.n_startup = int(self._setting("n_initial_points", 8))
        self.noise = self._setting("noise", 1e-6)
        self.xi = self._setting("xi", 0.01)          # EI exploration margin
        self.n_candidates = int(self._setting("n_candidates", 512))

    def _fit_predict(self, X: np.ndarray, y: np.ndarray,
                     Xq: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        mu, sd = y.mean(), y.std() + 1e-9
        yn = (y - mu) / sd
        # median-heuristic lengthscale
        if len(X) > 1:
            dists = np.sqrt(((X[:, None] - X[None, :]) ** 2).sum(-1))
            ls = max(np.median(dists[dists > 0]), 0.05)
        else:
            ls = 0.5
        K = _matern52(X, X, ls) + self.noise * np.eye(len(X))
        L = np.linalg.cholesky(K)
        alpha = np.linalg.solve(L.T, np.linalg.solve(L, yn))
        Ks = _matern52(Xq, X, ls)
        mean = Ks @ alpha
        v = np.linalg.solve(L, Ks.T)
        var = np.clip(1.0 - (v * v).sum(0), 1e-12, None)
        return mean * sd + mu, np.sqrt(var) * sd

    def _ei(self, mean: np.ndarray, std: np.ndarray, best: float) -> np.ndarray:
        z = (best - self.xi - mean) / std
        cdf = 0.5 * (1.0 + _erf(z / np.sqrt(2.0)))
        pdf = np.exp(-0.5 * z * z) / np.sqrt(2 * np.pi)
        return (best - self.xi - mean) * cdf + std * pdf

    def suggest(self, count, history):
        done = self._finished(history)
        out = []
        for _ in range(count):
            if len(done) < self.n_startup:
                out.append(self.space.sample(self.rng))
                continue
            X = np.stack([self.space.to_unit(t.params) for t in done])
            y = np.array([t.value for t in done])
            best_idx = int(np.argmin(y))
            cand = self.rng.uniform(size=(self.n_candidates, len(self.space)))
            # local candidates around the incumbent (exploitation cloud)
            local = np.clip(
                X[best_idx] + self.rng.normal(0, 0.08,
                                              (64, len(self.space))), 0, 1)
            cand = np.vstack([cand, local])
            mean, std = self._fit_predict(X, y, cand)
            ei = self._ei(mean, std, float(y.min()))
            pick = self.space.from_unit(cand[int(np.argmax(ei))])
            out.append(pick)
            # fantasy observation at posterior mean → diverse batches
            done = done + [type(done[0])(
                params=pick, value=float(mean[int(np.argmax(ei))]))]
        return out


def _erf(x: np.ndarray) -> np.ndarray:
    # Abramowitz-Stegun 7.1.26, max abs error 1.5e-7 — plenty for EI ranking
    sign = np.sign(x)
    x = np.abs(x)
    t = 1.0 / (1.0 + 0.3275911 * x)
    poly = t * (0.254829592 + t * (-0.284496736 + t * (
        1.421413741 + t * (-1.453152027 + t * 1.061405429))))
    return sign * (1.0 - poly * np.exp(-x * x))
