"""CMA-ES — covariance matrix adaptation evolution strategy (⊘ katib
pkg/suggestion/v1beta1/goptuna `cmaes`; Hansen's (mu/mu_w, lambda) update).

Operates on the unit cube with boundary clipping. Generation state (mean,
covariance, evolution paths) lives in the instance; ask/tell is mapped onto
the suggest/history interface by matching returned points against history.
On reconstruction after restart it re-seeds the mean from the best observed
point — the standard warm-start.
"""

from __future__ import annotations

import numpy as np

from kubeflow_tpu.hpo.algorithms.base import Algorithm, register


@register("cmaes")
class CMAES(Algorithm):
    def __init__(self, space, settings=None, seed=0):
        super().__init__(space, settings, seed)
        n = len(space)
        self.n = n
        self.lam = int(self._setting("population_size",
                                     4 + int(3 * np.log(n))))
        self.mu = self.lam // 2
        w = np.log(self.mu + 0.5) - np.log(np.arange(1, self.mu + 1))
        self.weights = w / w.sum()
        self.mu_eff = 1.0 / (self.weights ** 2).sum()
        self.sigma = self._setting("sigma", 0.3)
        self.cc = (4 + self.mu_eff / n) / (n + 4 + 2 * self.mu_eff / n)
        self.cs = (self.mu_eff + 2) / (n + self.mu_eff + 5)
        self.c1 = 2 / ((n + 1.3) ** 2 + self.mu_eff)
        self.cmu = min(1 - self.c1,
                       2 * (self.mu_eff - 2 + 1 / self.mu_eff)
                       / ((n + 2) ** 2 + self.mu_eff))
        self.damps = (1 + 2 * max(0.0, np.sqrt((self.mu_eff - 1)
                                               / (n + 1)) - 1) + self.cs)
        self.chi_n = np.sqrt(n) * (1 - 1 / (4 * n) + 1 / (21 * n * n))
        self.mean = np.full(n, 0.5)
        self.C = np.eye(n)
        self.pc = np.zeros(n)
        self.ps = np.zeros(n)
        self.gen = 0
        self._warmed = False
        self._pending: list[tuple[tuple, np.ndarray]] = []  # (key, z-vector)

    @staticmethod
    def _key(params: dict) -> tuple:
        return tuple(sorted((k, round(float(v), 10)
                             if isinstance(v, (int, float)) else v)
                            for k, v in params.items()))

    def _tell(self, history) -> None:
        """Fold any completed generation members back into the update."""
        done = {self._key(t.params): t.value for t in self._finished(history)}
        ready = [(k, x) for k, x in self._pending if k in done]
        if len(ready) < max(2, self.lam // 2):
            return
        ranked = sorted(ready, key=lambda kx: done[kx[0]])[:self.mu]
        X = np.stack([x for _, x in ranked])           # unit-cube points
        old_mean = self.mean.copy()
        self.mean = self.weights @ X
        y = (self.mean - old_mean) / self.sigma
        C_inv_sqrt = np.linalg.inv(np.linalg.cholesky(
            self.C + 1e-10 * np.eye(self.n))).T
        self.ps = ((1 - self.cs) * self.ps
                   + np.sqrt(self.cs * (2 - self.cs) * self.mu_eff)
                   * C_inv_sqrt @ y)
        hsig = (np.linalg.norm(self.ps)
                / np.sqrt(1 - (1 - self.cs) ** (2 * (self.gen + 1)))
                < (1.4 + 2 / (self.n + 1)) * self.chi_n)
        self.pc = ((1 - self.cc) * self.pc
                   + hsig * np.sqrt(self.cc * (2 - self.cc) * self.mu_eff) * y)
        artmp = (X - old_mean) / self.sigma
        self.C = ((1 - self.c1 - self.cmu) * self.C
                  + self.c1 * (np.outer(self.pc, self.pc)
                               + (not hsig) * self.cc * (2 - self.cc) * self.C)
                  + self.cmu * (artmp.T * self.weights) @ artmp)
        self.sigma *= np.exp((self.cs / self.damps)
                             * (np.linalg.norm(self.ps) / self.chi_n - 1))
        self.sigma = float(np.clip(self.sigma, 1e-4, 1.0))
        self.gen += 1
        self._pending = [(k, x) for k, x in self._pending if k not in done]

    def suggest(self, count, history):
        done = self._finished(history)
        if not self._warmed and done and not self._pending:
            # restart / warm start: center on the incumbent
            best = min(done, key=lambda t: t.value)
            self.mean = np.clip(self.space.to_unit(best.params), 0.05, 0.95)
            self._warmed = True
        self._tell(history)
        A = np.linalg.cholesky(self.C + 1e-10 * np.eye(self.n))
        out = []
        for _ in range(count):
            x = np.clip(self.mean + self.sigma
                        * A @ self.rng.standard_normal(self.n), 0.0, 1.0)
            params = self.space.from_unit(x)
            self._pending.append((self._key(params), x))
            out.append(params)
        return out
