"""Population Based Training (Jaderberg et al. 2017) — ⊘ katib
pkg/suggestion/v1beta1/pbt/service.py.

Katib's PBT service evolves a fixed-size population generation by
generation: when a generation of trials completes, the bottom
`truncation_threshold` fraction is replaced by copies of uniformly-drawn
top performers (exploit) whose hyperparameters are then perturbed or
resampled (explore); survivors carry their parameters forward unchanged.

Checkpoint lineage: each suggested assignment carries a `pbt_parent` key —
the 0-based index (into the experiment's finished-trial history) of the
trial whose weights this member should warm-start from, or -1 for a fresh
start. Trial templates can reference it via trialParameters (e.g. to build
a restore path), exactly how Katib's PBT passes checkpoint directories
through annotations. Extra assignment keys ride along without being part
of the search space.

algorithmSettings (Katib names):
    n_population          population / generation size       (default 8)
    truncation_threshold  fraction exploited each generation (default 0.2)
    resample_probability  P(resample a param from scratch vs perturb) (0.25)
    perturb_factors       comma-separated multipliers        ("0.8,1.2")

Like all algorithms here, state reconstructs from history alone
(resumePolicy: FromVolume): generations are consecutive chunks of the
finished-trial list.
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

from kubeflow_tpu.hpo.algorithms.base import Algorithm, TrialResult, register


@register("pbt")
class PopulationBasedTraining(Algorithm):
    exhaustible = False   # an empty batch means "generation in flight"

    def __init__(self, space, settings=None, seed=0):
        super().__init__(space, settings, seed)
        self.n_pop = int(self._setting("n_population", 8))
        if self.n_pop < 2:
            raise ValueError("pbt needs n_population >= 2")
        self.truncation = self._setting("truncation_threshold", 0.2)
        if not 0.0 < self.truncation <= 0.5:
            raise ValueError("truncation_threshold must be in (0, 0.5]")
        self.resample_p = self._setting("resample_probability", 0.25)
        factors = str(self.settings.get("perturb_factors", "0.8,1.2"))
        self.factors = tuple(float(f) for f in factors.split(","))
        # suggestions handed out but not yet reflected in finished history
        self._queue: list[dict[str, Any]] = []
        self._generations_emitted = 0

    # -- explore --------------------------------------------------------------

    def _explore(self, params: dict[str, Any]) -> dict[str, Any]:
        """Perturb each space parameter: numeric values are multiplied by a
        random perturb factor (clamped to bounds, re-quantized through the
        unit embedding so int/step constraints hold); categoricals and any
        param hit by resample_probability draw fresh."""
        out = dict(params)
        for p in self.space.parameters:
            if self.rng.uniform() < self.resample_p \
                    or p.type in ("categorical", "discrete"):
                out[p.name] = p.sample(self.rng)
                continue
            factor = self.factors[self.rng.integers(len(self.factors))]
            x = float(params[p.name]) * factor
            x = min(max(x, float(p.min)), float(p.max))
            out[p.name] = p.from_unit(p.to_unit(x))
        return out

    # -- generation advance ---------------------------------------------------

    def _next_generation(self, gen: list[TrialResult],
                         base_index: int) -> list[dict[str, Any]]:
        """gen = one finished generation (history order); base_index = index
        of gen[0] in the full finished history (for pbt_parent lineage)."""
        ranked = sorted(range(len(gen)), key=lambda i: (
            gen[i].value if gen[i].ok else np.inf))
        k = max(1, int(np.ceil(self.truncation * len(gen))))
        top, bottom = ranked[:k], set(ranked[-k:])
        members = []
        for i, t in enumerate(gen):
            if i in bottom or not t.ok:
                # exploit: clone a uniformly-drawn top performer, explore
                src = top[self.rng.integers(len(top))]
                params = self._explore(gen[src].params)
                parent = base_index + src
            else:
                # survivor: same hyperparameters, continue from own weights
                params = {p.name: t.params[p.name]
                          for p in self.space.parameters}
                parent = base_index + i
            members.append({**params, "pbt_parent": parent})
        return members

    def suggest(self, count: int,
                history: Sequence[TrialResult]) -> list[dict[str, Any]]:
        finished = list(history)   # includes failed: they occupy a slot
        # generations are consecutive n_pop-sized chunks of history; the
        # frontier generation is the one currently being filled
        frontier = len(finished) // self.n_pop
        if self._generations_emitted <= frontier:
            if self._generations_emitted < frontier:
                # restart / missed generations: anything queued is stale
                self._queue.clear()
            # members still owed for the frontier = population size minus
            # slots already handed out (handed-out > finished when trials
            # are in flight — those slots must NOT be re-emitted)
            issued = self.issued if self.issued is not None \
                else len(finished)
            taken = max(issued, frontier * self.n_pop)
            n_missing = max(0, (frontier + 1) * self.n_pop - taken)
            if n_missing and frontier == 0:
                members = [{**self.space.sample(self.rng), "pbt_parent": -1}
                           for _ in range(n_missing)]
            elif n_missing:
                base = (frontier - 1) * self.n_pop
                gen = finished[base:base + self.n_pop]
                # position-wise generation build; the tail slice holds the
                # positions nothing has been handed out for yet
                members = self._next_generation(gen, base)[-n_missing:]
            else:
                members = []
            self._queue.extend(members)
            self._generations_emitted = frontier + 1
        out, self._queue = self._queue[:count], self._queue[count:]
        return out
