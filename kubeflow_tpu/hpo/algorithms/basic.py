"""Model-free suggestion algorithms: random, grid, quasirandom.

⊘ katib pkg/suggestion/v1beta1/hyperopt (random), pkg/suggestion/v1beta1/chocolate
grid (older vintages), goptuna sobol. The quasirandom sampler here is a
scrambled Halton sequence — same role as Katib's "sobol" (low-discrepancy
space filling); registered under both names.
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

from kubeflow_tpu.hpo.algorithms.base import Algorithm, TrialResult, register


@register("random")
class RandomSearch(Algorithm):
    def suggest(self, count, history):
        return [self.space.sample(self.rng) for _ in range(count)]


@register("grid")
class GridSearch(Algorithm):
    """Enumerates the full cartesian grid in order, continuing from wherever
    history left off. Continuous axes are discretized to `grid_points_per_axis`
    (default 4) unless they carry a step."""

    def __init__(self, space, settings=None, seed=0):
        super().__init__(space, settings, seed)
        per_axis = int(self._setting("grid_points_per_axis", 4))
        self._axes = [p.grid(per_axis) for p in self.space.parameters]
        self._sizes = [len(a) for a in self._axes]
        self._total = int(np.prod(self._sizes))
        self._cursor = 0

    def _point(self, i: int) -> dict[str, Any]:
        out = {}
        for axis, size, param in zip(self._axes, self._sizes,
                                     self.space.parameters):
            out[param.name] = axis[i % size]
            i //= size
        return out

    def suggest(self, count, history):
        self._cursor = max(self._cursor, len(history))
        out = []
        while len(out) < count and self._cursor < self._total:
            out.append(self._point(self._cursor))
            self._cursor += 1
        return out   # exhausted grid → shorter batch; experiment completes


def _halton(index: int, base: int) -> float:
    f, r = 1.0, 0.0
    while index > 0:
        f /= base
        r += f * (index % base)
        index //= base
    return r


_PRIMES = (2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37, 41, 43, 47, 53, 59, 61)


@register("sobol")
@register("quasirandom")
class QuasiRandom(Algorithm):
    """Scrambled-Halton low-discrepancy sequence over the unit cube; decoded
    through the space embedding. Deterministic given the seed."""

    def __init__(self, space, settings=None, seed=0):
        super().__init__(space, settings, seed)
        if len(space) > len(_PRIMES):
            raise ValueError(
                f"quasirandom supports <= {len(_PRIMES)} dimensions")
        self._shift = self.rng.uniform(size=len(space))  # Cranley-Patterson
        self._cursor = 0

    def suggest(self, count, history):
        self._cursor = max(self._cursor, len(history))
        out = []
        for _ in range(count):
            self._cursor += 1   # skip index 0 (all-zeros corner)
            u = np.array([_halton(self._cursor, _PRIMES[d])
                          for d in range(len(self.space))])
            out.append(self.space.from_unit((u + self._shift) % 1.0))
        return out


@register("hyperband")
class Hyperband(Algorithm):
    """Successive-halving resource schedule (Li et al. 2018), ⊘ katib
    pkg/suggestion/v1beta1/hyperband.

    Settings: `resource_name` (a parameter in the space — typically epochs or
    train steps), `eta` (halving factor, default 3). Brackets are derived from
    the resource parameter's min/max. Each call tops up the current rung with
    random configs at the rung's resource level; when a rung's trials finish,
    the best 1/eta are promoted with eta× the resource.
    """

    def __init__(self, space, settings=None, seed=0):
        super().__init__(space, settings, seed)
        self.resource = self.settings.get("resource_name")
        if not self.resource or self.resource not in space.names():
            raise ValueError("hyperband requires algorithmSettings."
                             "resource_name naming a space parameter")
        self.eta = self._setting("eta", 3.0)
        rp = next(p for p in space.parameters if p.name == self.resource)
        if rp.min is None or rp.max is None:
            raise ValueError("hyperband resource parameter needs min/max")
        self.r_min, self.r_max = float(rp.min), float(rp.max)
        self._rp = rp
        # s_max+1 rungs: resource r_max/eta^s ... r_max
        self.s_max = int(np.floor(np.log(self.r_max / self.r_min)
                                  / np.log(self.eta)))
        self._rung = 0
        self._rung_size = int(self.eta ** self.s_max)
        self._promoted: list[dict[str, Any]] = []

    def _resource_at(self, rung: int) -> Any:
        r = self.r_max / self.eta ** (self.s_max - rung)
        return self._rp.from_unit(self._rp.to_unit(
            min(max(r, self.r_min), self.r_max)))

    def _rung_of(self, t: TrialResult) -> int:
        r = float(t.params.get(self.resource, self.r_min))
        return int(round(np.log(max(r / (self.r_max / self.eta ** self.s_max),
                                    1.0)) / np.log(self.eta)))

    def suggest(self, count, history: Sequence[TrialResult]):
        done = self._finished(history)
        by_rung: dict[int, list[TrialResult]] = {}
        for t in done:
            by_rung.setdefault(self._rung_of(t), []).append(t)
        # promote: best 1/eta of the deepest completed rung not yet advanced
        cur = by_rung.get(self._rung, [])
        if len(cur) >= self._rung_size and self._rung < self.s_max:
            keep = max(1, int(len(cur) / self.eta))
            ranked = sorted(cur, key=lambda t: t.value)[:keep]
            self._rung += 1
            self._rung_size = keep
            res = self._resource_at(self._rung)
            self._promoted = [
                {**t.params, self.resource: res} for t in ranked]
        out = []
        while self._promoted and len(out) < count:
            out.append(self._promoted.pop(0))
        res = self._resource_at(self._rung)
        while len(out) < count:
            p = self.space.sample(self.rng)
            p[self.resource] = res
            out.append(p)
        return out
