"""Suggestion-algorithm interface — Katib's suggestion services behind the
`GetSuggestions` gRPC API (SURVEY.md §2.3, ⊘ katib
pkg/suggestion/v1beta1/{hyperopt,skopt,...} + api/v1beta1/suggestion.proto).

Here a suggestion "service" is an Algorithm instance held by the suggestion
controller (one per Experiment, like Katib's per-experiment Deployment).
Convention: algorithms MINIMIZE. The experiment controller negates values for
maximize objectives before handing history over, so algorithm code never
branches on objective direction.

Stateful algorithms (CMA-ES, hyperband) keep internal generation state; all
algorithms must also tolerate reconstruction from history alone (experiment
resume after restart — Katib's `resumePolicy: FromVolume`).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Sequence

import numpy as np

from kubeflow_tpu.hpo.space import SearchSpace


@dataclass(frozen=True)
class TrialResult:
    """One completed/failed/pruned trial as seen by the algorithm."""
    params: dict[str, Any]
    value: float | None          # objective, lower is better; None if no metric
    status: str = "Succeeded"    # Succeeded | Failed | EarlyStopped

    @property
    def ok(self) -> bool:
        return self.value is not None and np.isfinite(self.value)


class Algorithm:
    """Subclass: implement `suggest`. Settings arrive as the Katib
    `algorithmSettings` string map; subclasses read what they need."""

    name = ""
    # an empty suggest() batch normally means the algorithm enumerated its
    # whole space (grid) and the experiment may complete; generation-gated
    # algorithms (PBT) set False: empty means "waiting on running trials"
    exhaustible = True
    # set by the suggestion controller before each suggest() call: total
    # assignments already handed out (>= finished history, since handed-out
    # trials may still be running). Generation-gated algorithms need it to
    # avoid re-emitting in-flight population slots after a restart.
    issued: int | None = None

    def __init__(self, space: SearchSpace,
                 settings: dict[str, Any] | None = None, seed: int = 0):
        self.space = space
        self.settings = dict(settings or {})
        if "random_state" in self.settings:  # Katib's setting name
            seed = int(self.settings["random_state"])
        self.rng = np.random.default_rng(seed)

    def suggest(self, count: int,
                history: Sequence[TrialResult]) -> list[dict[str, Any]]:
        raise NotImplementedError

    # -- shared helpers -------------------------------------------------------

    def _setting(self, key: str, default: float) -> float:
        return float(self.settings.get(key, default))

    def _finished(self, history: Sequence[TrialResult]) -> list[TrialResult]:
        return [t for t in history if t.ok]

_REGISTRY: dict[str, Callable[..., Algorithm]] = {}


def register(name: str):
    def deco(cls):
        _REGISTRY[name] = cls
        cls.name = name
        return cls
    return deco


def make_algorithm(name: str, space: SearchSpace,
                   settings: dict[str, Any] | None = None,
                   seed: int = 0) -> Algorithm:
    if name not in _REGISTRY:
        raise ValueError(
            f"unknown algorithm {name!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[name](space, settings, seed)


def algorithm_names() -> list[str]:
    return sorted(_REGISTRY)
