"""Tree-structured Parzen Estimator — Katib's default model-based algorithm
(⊘ katib pkg/suggestion/v1beta1/hyperopt `tpe`; Bergstra et al. 2011).

Per-dimension TPE over the unit-cube embedding: split observed points into
good (best gamma-quantile) and bad sets, fit Parzen windows l(x) and g(x),
sample candidates from l, keep the candidate maximizing l(x)/g(x).
Categorical axes use re-weighted categorical distributions instead of
Gaussians, as in hyperopt.
"""

from __future__ import annotations

import numpy as np

from kubeflow_tpu.hpo.algorithms.base import Algorithm, register


def _parzen_logpdf(x: np.ndarray, centers: np.ndarray, bw: float) -> np.ndarray:
    """log of a uniform-weight Gaussian mixture on [0,1], one kernel per
    center, with a flat prior kernel for unexplored mass."""
    # prior: uniform on [0,1] == N(0.5, 1) truncated-ish; use wide gaussian
    centers = np.concatenate([centers, [0.5]])
    bws = np.full(len(centers), bw)
    bws[-1] = 1.0
    d = (x[:, None] - centers[None, :]) / bws[None, :]
    log_k = -0.5 * d * d - np.log(bws[None, :] * np.sqrt(2 * np.pi))
    m = log_k.max(axis=1, keepdims=True)
    return (m + np.log(np.exp(log_k - m).sum(axis=1, keepdims=True))
            ).ravel() - np.log(len(centers))


@register("tpe")
class TPE(Algorithm):
    def __init__(self, space, settings=None, seed=0):
        super().__init__(space, settings, seed)
        self.gamma = self._setting("gamma", 0.25)
        self.n_startup = int(self._setting("n_initial_points", 10))
        self.n_candidates = int(self._setting("n_ei_candidates", 24))

    def suggest(self, count, history):
        done = self._finished(history)
        out = []
        for _ in range(count):
            if len(done) < self.n_startup:
                out.append(self.space.sample(self.rng))
                continue
            X = np.stack([self.space.to_unit(t.params) for t in done])
            y = np.array([t.value for t in done])
            n_good = max(1, int(np.ceil(self.gamma * len(done))))
            order = np.argsort(y)
            good, bad = X[order[:n_good]], X[order[n_good:]]
            point = np.empty(len(self.space))
            for d, param in enumerate(self.space.parameters):
                k = param.n_choices
                if param.type == "categorical" and k:
                    point[d] = self._categorical_dim(good[:, d], bad[:, d], k)
                else:
                    point[d] = self._continuous_dim(good[:, d], bad[:, d])
            out.append(self.space.from_unit(point))
            # virtual result at the good-set median keeps a batch diverse
            done = done + [type(done[0])(params=out[-1],
                                         value=float(np.median(y)))]
        return out

    def _continuous_dim(self, good: np.ndarray, bad: np.ndarray) -> float:
        bw_g = max(1.0 / (1 + len(good)), good.std() + 1e-3)
        bw_b = max(1.0 / (1 + len(bad)), bad.std() + 1e-3 if len(bad) else 1.0)
        idx = self.rng.integers(0, len(good) + 1, size=self.n_candidates)
        cand = np.where(
            idx < len(good),
            np.clip(good[np.minimum(idx, len(good) - 1)]
                    + self.rng.normal(0, bw_g, self.n_candidates), 0, 1),
            self.rng.uniform(size=self.n_candidates))
        score = _parzen_logpdf(cand, good, bw_g) - _parzen_logpdf(
            cand, bad if len(bad) else np.array([0.5]), bw_b)
        return float(cand[np.argmax(score)])

    def _categorical_dim(self, good: np.ndarray, bad: np.ndarray,
                         k: int) -> float:
        def weights(col: np.ndarray) -> np.ndarray:
            idx = np.minimum((col * k).astype(int), k - 1)
            return np.bincount(idx, minlength=k) + 1.0  # +1 prior
        wg = weights(good)
        wb = weights(bad) if len(bad) else np.ones(k)
        ratio = (wg / wg.sum()) / (wb / wb.sum())
        # sample from l, weight by ratio: draw candidates ∝ wg, pick max ratio
        cands = self.rng.choice(k, size=min(self.n_candidates, 4 * k),
                                p=wg / wg.sum())
        best = cands[np.argmax(ratio[cands])]
        return (best + 0.5) / k
