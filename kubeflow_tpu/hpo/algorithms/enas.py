"""ENAS-style reinforcement-learned architecture search (SURVEY.md §2.3,
⊘ katib pkg/suggestion/v1beta1/nas ENAS suggestion service).

Katib's ENAS keeps an LSTM controller in the suggestion pod: it samples one
operation per layer, trials train a SHARED supernet with the sampled ops
and report a reward, and the controller updates by REINFORCE. The analog
here:

  - **Controller**: a factorized per-parameter categorical policy — one
    logits vector per (categorical) search parameter — updated by
    REINFORCE with an exponential-moving-average baseline. Over the
    layerwise `nasConfig` spaces Katib feeds ENAS, the factorized policy
    expresses the same per-layer distributions the LSTM emits; the LSTM's
    extra sequence coupling is dropped deliberately (it is the part of
    ENAS that rarely changes the argmax architecture, and the policy
    gradient is identical). The policy state is reconstructed from trial
    history alone, so experiment resume (`resumePolicy: FromVolume`)
    replays the updates deterministically.
  - **Weight sharing**: trials are ordinary training jobs; pointing the
    trial template's checkpoint directory at a SHARED location makes
    every trial warm-start from the latest supernet weights through the
    ordinary checkpoint/resume machinery (training/checkpoint.py) — the
    job-based twin of ENAS's shared-supernet trick. The controller itself
    is agnostic to whether trials share weights.

Algorithms MINIMIZE (base.py convention), so the REINFORCE reward is the
negated objective.

    algorithm:
      algorithmName: enas
      algorithmSettings:
        learning_rate: "0.25"      # policy-gradient step on the logits
        baseline_decay: "0.7"      # EMA reward baseline
        temperature: "1.0"         # sampling temperature on the logits
        random_state: "0"
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

from kubeflow_tpu.hpo.algorithms.base import (Algorithm, TrialResult,
                                              register)
from kubeflow_tpu.hpo.space import SpaceError


@register("enas")
class EnasAlgorithm(Algorithm):
    """REINFORCE over a factorized categorical policy."""

    def __init__(self, space, settings=None, seed: int = 0):
        super().__init__(space, settings, seed)
        self._cat = [p for p in space.parameters
                     if p.type in ("categorical", "discrete")]
        if not self._cat:
            raise SpaceError(
                "enas needs at least one categorical/discrete parameter "
                "(expand a nasConfig, or use a numeric algorithm)")
        # non-categorical co-parameters (e.g. a learning rate riding the
        # same experiment) are sampled uniformly — the controller only
        # learns the architecture choices
        self._rest = [p for p in space.parameters if p not in self._cat]
        self.lr = self._setting("learning_rate", 0.25)
        self.baseline_decay = self._setting("baseline_decay", 0.7)
        self.temperature = max(self._setting("temperature", 1.0), 1e-3)

    # -- policy state, rebuilt from history every call ----------------------

    def _fit(self, history: Sequence[TrialResult]):
        """Replay REINFORCE over finished trials in order. Stateless
        across calls by design: the policy is a pure function of history,
        so controller state survives suggestion-service restarts without
        any persisted volume."""
        logits = {p.name: np.zeros(len(p.values)) for p in self._cat}
        baseline = None
        for t in self._finished(history):
            reward = -t.value  # minimize -> reward is the negated loss
            if baseline is None:
                baseline = reward
            adv = reward - baseline
            baseline = (self.baseline_decay * baseline
                        + (1 - self.baseline_decay) * reward)
            for p in self._cat:
                if p.name not in t.params:
                    continue
                try:
                    idx = list(p.values).index(t.params[p.name])
                except ValueError:
                    continue  # param values edited mid-experiment
                lg = logits[p.name]
                probs = _softmax(lg / self.temperature)
                # d/d_logits log pi(idx) = onehot(idx) - probs
                grad = -probs
                grad[idx] += 1.0
                lg += self.lr * adv * grad
        return logits

    def suggest(self, count: int,
                history: Sequence[TrialResult]) -> list[dict[str, Any]]:
        logits = self._fit(history)
        out = []
        for _ in range(count):
            params: dict[str, Any] = {}
            for p in self._cat:
                probs = _softmax(logits[p.name] / self.temperature)
                params[p.name] = p.values[int(self.rng.choice(
                    len(p.values), p=probs))]
            for p in self._rest:
                params[p.name] = p.sample(self.rng)
            out.append(params)
        return out

    def best_architecture(self, history: Sequence[TrialResult]
                          ) -> dict[str, Any]:
        """The policy's argmax choice per parameter — ENAS's final derived
        architecture (Katib surfaces it when the experiment completes)."""
        logits = self._fit(history)
        return {p.name: p.values[int(np.argmax(logits[p.name]))]
                for p in self._cat}


def _softmax(x: np.ndarray) -> np.ndarray:
    e = np.exp(x - np.max(x))
    return e / e.sum()
