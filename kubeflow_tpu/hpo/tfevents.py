"""TensorFlow event-file scalar codec + tailing collector — Katib's
TensorFlowEvent metrics collector (SURVEY.md §2.3, ⊘ katib
pkg/metricscollector/v1beta1/tfevent-metricscollector).

The reference's collector reads trial tfevents logdirs with the TF event
reader and reports scalars to the db-manager. Importing tensorflow costs
tens of seconds and hundreds of MB on this 1-core box, so this module
parses the format directly — it is small and stable:

  TFRecord framing: u64 length, u32 masked-crc32c(length), payload,
                    u32 masked-crc32c(payload)
  Payload: an `Event` proto — step=2 (varint), summary=5 (message) with
           repeated Value{tag=1 (string), simple_value=2 (float),
           tensor=8 (TF2 scalars: float_val=5 / tensor_content=4)}

Both the TF1-style `simple_value` and TF2-style scalar-tensor encodings
are handled; a writer (valid masked CRCs, simple_value encoding) is
included so trainers can emit tfevents without tensorflow installed.
"""

from __future__ import annotations

import os
import struct
import threading
from typing import Iterator, Sequence

from kubeflow_tpu.hpo.observations import ObservationDB

# -- crc32c (Castagnoli), TFRecord masking ------------------------------------

_CRC_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ (0x82F63B78 if _c & 1 else 0)
    _CRC_TABLE.append(_c)


def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ _CRC_TABLE[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# -- protobuf wire helpers ----------------------------------------------------


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    out = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def _iter_fields(buf: bytes) -> Iterator[tuple[int, int, bytes | int]]:
    """Yields (field_number, wire_type, value) over a serialized message.
    Length-delimited values are bytes; varints ints; fixed32/64 raw ints."""
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, pos = _read_varint(buf, pos)
        elif wire == 1:
            val = struct.unpack_from("<Q", buf, pos)[0]
            pos += 8
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            val = struct.unpack_from("<I", buf, pos)[0]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _scalar_from_tensor(buf: bytes) -> float | None:
    """TF2 writes scalars as TensorProto: float_val=5 (packed or single)
    or raw tensor_content=4 little-endian float32."""
    for field, wire, val in _iter_fields(buf):
        if field == 5 and wire == 5:
            return struct.unpack("<f", struct.pack("<I", val))[0]
        if field == 5 and wire == 2 and len(val) >= 4:
            return struct.unpack_from("<f", val, 0)[0]
        if field == 4 and wire == 2 and len(val) >= 4:
            return struct.unpack_from("<f", val, 0)[0]
    return None


# -- event file read/write ----------------------------------------------------


def read_events(path: str) -> Iterator[tuple[int, str, float]]:
    """Yields (step, tag, scalar_value) from one tfevents file. Truncated
    trailing records (a live writer mid-append) stop iteration cleanly."""
    with open(path, "rb") as f:
        data = f.read()
    pos = 0
    while pos + 12 <= len(data):
        (length,) = struct.unpack_from("<Q", data, pos)
        end = pos + 12 + length + 4
        if end > len(data):
            return   # partial tail: next poll re-reads from a clean offset
        payload = data[pos + 12:pos + 12 + length]
        pos = end
        step = 0
        values: list[tuple[str, float]] = []
        for field, wire, val in _iter_fields(payload):
            if field == 2 and wire == 0:
                step = val
            elif field == 5 and wire == 2:   # summary
                for f2, w2, v2 in _iter_fields(val):
                    if f2 != 1 or w2 != 2:
                        continue
                    tag, scalar = None, None
                    for f3, w3, v3 in _iter_fields(v2):
                        if f3 == 1 and w3 == 2:
                            tag = v3.decode("utf-8", "replace")
                        elif f3 == 2 and w3 == 5:   # simple_value
                            scalar = struct.unpack(
                                "<f", struct.pack("<I", v3))[0]
                        elif f3 == 8 and w3 == 2:   # tensor (TF2 scalar)
                            scalar = _scalar_from_tensor(v3)
                    if tag is not None and scalar is not None:
                        values.append((tag, scalar))
        for tag, scalar in values:
            yield step, tag, scalar


def event_files(logdir: str) -> list[str]:
    """tfevents files under a logdir (or the file itself), sorted for
    deterministic multi-file replay."""
    if os.path.isfile(logdir):
        return [logdir]
    out = []
    for root, _, files in os.walk(logdir):
        for fn in files:
            if "tfevents" in fn:
                out.append(os.path.join(root, fn))
    return sorted(out)


class EventWriter:
    """Minimal tfevents scalar writer (valid TFRecord masked CRCs +
    simple_value summaries) — lets trainers emit TensorBoard-readable
    logs without importing tensorflow."""

    def __init__(self, logdir: str, filename: str | None = None):
        os.makedirs(logdir, exist_ok=True)
        self.path = os.path.join(
            logdir, filename or "events.out.tfevents.kubeflow-tpu")
        self._fh = open(self.path, "ab")

    def write_scalar(self, step: int, tag: str, value: float) -> None:
        tag_b = tag.encode()
        value_msg = (bytes([0x0A]) + _varint(len(tag_b)) + tag_b
                     + bytes([0x15]) + struct.pack("<f", float(value)))
        summary = bytes([0x0A]) + _varint(len(value_msg)) + value_msg
        event = (bytes([0x10]) + _varint(step)
                 + bytes([0x2A]) + _varint(len(summary)) + summary)
        header = struct.pack("<Q", len(event))
        self._fh.write(header + struct.pack("<I", _masked_crc(header))
                       + event + struct.pack("<I", _masked_crc(event)))
        self._fh.flush()

    def close(self) -> None:
        self._fh.close()


# -- tailing collector --------------------------------------------------------


class TfEventsTail:
    """Follows a tfevents logdir, reporting new scalar records into the
    observation DB — the FileTail twin for TensorFlowEvent collectors.
    Replays whole files on growth (tfevents are append-only and trial-
    sized), deduplicating by (file, record-count) watermark."""

    def __init__(self, db: ObservationDB, trial: str, logdir: str,
                 metric_names: Sequence[str], poll: float = 0.2):
        self.db = db
        self.trial = trial
        self.logdir = logdir
        self.wanted = set(metric_names)
        self.poll = poll
        self._seen: dict[str, int] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"tfevents-collector-{self.trial}")
        self._thread.start()

    def stop(self, final_pass: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if final_pass:
            self._drain()

    def _run(self) -> None:
        while not self._stop.wait(self.poll):
            self._drain()

    def _drain(self) -> None:
        for path in event_files(self.logdir):
            seen = self._seen.get(path, 0)
            try:
                records = list(read_events(path))
            except (OSError, ValueError, IndexError, struct.error):
                # malformed/foreign file in the logdir: skip it, keep the
                # collector thread alive for the well-formed ones
                continue
            for step, tag, value in records[seen:]:
                if tag in self.wanted:
                    self.db.report(self.trial, tag, value, step)
            self._seen[path] = len(records)
