"""Observation-log store — Katib's db-manager (SURVEY.md §2.3, ⊘ katib
`api/v1beta1/api.proto` ReportObservationLog/GetObservationLog over MySQL).

Stores per-trial metric time series. Backed by sqlite (the environment's
MySQL stand-in) so logs survive process restarts and experiments can resume
(`resumePolicy`), or fully in-memory for tests. A process-wide default
instance lets in-process trial workers report metrics directly — the
metrics-collector sidecar path for thread-backend pods.
"""

from __future__ import annotations

import sqlite3
import threading
import time
from dataclasses import dataclass
from typing import Iterable


@dataclass(frozen=True)
class Observation:
    trial: str
    metric: str
    value: float
    step: int
    timestamp: float


class ObservationDB:
    """Thread-safe metric log: report / get / latest / delete."""

    def __init__(self, path: str = ":memory:"):
        self._lock = threading.Lock()
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS observation_logs ("
            " trial TEXT NOT NULL, metric TEXT NOT NULL,"
            " value REAL NOT NULL, step INTEGER NOT NULL, ts REAL NOT NULL)")
        self._db.execute(
            "CREATE INDEX IF NOT EXISTS idx_trial_metric"
            " ON observation_logs (trial, metric, step)")
        self._db.commit()

    def report(self, trial: str, metric: str, value: float,
               step: int = 0, timestamp: float | None = None) -> None:
        with self._lock:
            self._db.execute(
                "INSERT INTO observation_logs VALUES (?,?,?,?,?)",
                (trial, metric, float(value), int(step),
                 time.time() if timestamp is None else timestamp))
            self._db.commit()

    def report_many(self, obs: Iterable[Observation]) -> None:
        with self._lock:
            self._db.executemany(
                "INSERT INTO observation_logs VALUES (?,?,?,?,?)",
                [(o.trial, o.metric, o.value, o.step, o.timestamp)
                 for o in obs])
            self._db.commit()

    def get(self, trial: str, metric: str | None = None) -> list[Observation]:
        q = ("SELECT trial, metric, value, step, ts FROM observation_logs"
             " WHERE trial = ?")
        args: tuple = (trial,)
        if metric is not None:
            q += " AND metric = ?"
            args += (metric,)
        q += " ORDER BY step, ts"
        with self._lock:
            rows = self._db.execute(q, args).fetchall()
        return [Observation(*r) for r in rows]

    def latest(self, trial: str, metric: str) -> Observation | None:
        with self._lock:
            row = self._db.execute(
                "SELECT trial, metric, value, step, ts FROM observation_logs"
                " WHERE trial = ? AND metric = ?"
                " ORDER BY step DESC, ts DESC LIMIT 1",
                (trial, metric)).fetchone()
        return None if row is None else Observation(*row)

    def best(self, trial: str, metric: str, maximize: bool) -> float | None:
        with self._lock:
            row = self._db.execute(
                f"SELECT {'MAX' if maximize else 'MIN'}(value)"
                " FROM observation_logs WHERE trial = ? AND metric = ?",
                (trial, metric)).fetchone()
        return None if row is None or row[0] is None else float(row[0])

    def delete_trial(self, trial: str) -> None:
        with self._lock:
            self._db.execute(
                "DELETE FROM observation_logs WHERE trial = ?", (trial,))
            self._db.commit()

    def close(self) -> None:
        with self._lock:
            self._db.close()


_default: ObservationDB | None = None
_default_lock = threading.Lock()


def default_db() -> ObservationDB:
    """Process-wide DB used by in-process workers to report metrics
    (set_default_db from tests/clusters to scope it)."""
    global _default
    with _default_lock:
        if _default is None:
            _default = ObservationDB()
        return _default


def set_default_db(db: ObservationDB | None) -> None:
    global _default
    with _default_lock:
        _default = db


def clear_default_db(db: ObservationDB) -> None:
    """Unset the process default only if it is still `db` — lets an owner
    (e.g. Platform.stop) release it without clobbering another live owner."""
    global _default
    with _default_lock:
        if _default is db:
            _default = None


def report_metric(trial: str, metric: str, value: float, step: int = 0) -> None:
    """Convenience for worker code: `report_metric(env['KTPU_TRIAL'], ...)`."""
    default_db().report(trial, metric, value, step)
