"""Hyperparameter optimization — the Katib analog (SURVEY.md §2.3).

Experiment/Suggestion/Trial CRD-shaped resources reconciled by controllers;
suggestion algorithms (random, grid, sobol, TPE, GP-bayesian, CMA-ES,
hyperband); metrics collection into an observation DB; median-stop early
stopping.

    from kubeflow_tpu.control import Cluster
    from kubeflow_tpu import hpo

    cluster = Cluster()
    db = hpo.add_hpo_controllers(cluster)   # + JAXJobController separately
"""

from kubeflow_tpu.hpo.algorithms import (TrialResult, algorithm_names,
                                         make_algorithm)
from kubeflow_tpu.hpo.collector import FileTail, collect_text
from kubeflow_tpu.hpo.earlystopping import MedianStop, make_early_stopping
from kubeflow_tpu.hpo.experiment import (EXPERIMENT_KIND, SUGGESTION_KIND,
                                         ExperimentController,
                                         SuggestionController,
                                         validate_experiment)
from kubeflow_tpu.hpo.observations import (Observation, ObservationDB,
                                           default_db, report_metric,
                                           set_default_db)
from kubeflow_tpu.hpo.space import Parameter, SearchSpace, SpaceError
from kubeflow_tpu.hpo.trial import (EXPERIMENT_LABEL, TRIAL_KIND,
                                    TrialController, substitute,
                                    trial_finished)


def add_hpo_controllers(cluster, db: ObservationDB | None = None,
                        metrics_dir: str | None = None) -> ObservationDB:
    """Wire the three HPO controllers onto a Cluster sharing one observation
    DB; returns the DB (also installed as the in-process default so thread
    workers can `report_metric`)."""
    db = db or ObservationDB()
    set_default_db(db)
    cluster.add(ExperimentController)
    cluster.add(SuggestionController, db=db)
    cluster.add(TrialController, db=db, metrics_dir=metrics_dir)
    return db


__all__ = [
    "EXPERIMENT_KIND", "EXPERIMENT_LABEL", "SUGGESTION_KIND", "TRIAL_KIND",
    "ExperimentController", "FileTail", "MedianStop", "Observation",
    "ObservationDB", "Parameter", "SearchSpace", "SpaceError",
    "SuggestionController", "TrialController", "TrialResult",
    "add_hpo_controllers", "algorithm_names", "collect_text", "default_db",
    "make_algorithm", "make_early_stopping", "report_metric",
    "set_default_db", "substitute", "trial_finished", "validate_experiment",
]
