"""Suggestion service over gRPC — Katib's per-experiment algorithm
Deployment + `GetSuggestions` API (SURVEY.md §2.3 ⊘ katib
`pkg/suggestion/v1beta1/*` services, `suggestion_controller.go` gRPC
client). The in-process suggestion controller uses the algorithms
directly; this service is the out-of-process deployment shape — the same
algorithm registry behind the same wire API the reference uses, so an
external experiment controller (or the reference's, pointed here) can
drive this framework's algorithms.

Like serving/grpc_server.py, service wiring is hand-registered (no
grpcio-tools in the image) over protoc-generated messages
(hpo/protos/suggestion_pb2.py).
"""

from __future__ import annotations

from concurrent import futures
from typing import Any

from kubeflow_tpu.hpo.algorithms import TrialResult, make_algorithm
from kubeflow_tpu.hpo.protos import suggestion_pb2 as pb
from kubeflow_tpu.hpo.space import SearchSpace, SpaceError

SERVICE = "suggestion.Suggestion"


def _space_from_pb(exp: "pb.ExperimentSpec") -> SearchSpace:
    specs = []
    for p in exp.parameters:
        fs: dict[str, Any] = {}
        if p.feasible_space.min:
            fs["min"] = p.feasible_space.min
        if p.feasible_space.max:
            fs["max"] = p.feasible_space.max
        if p.feasible_space.step:
            fs["step"] = p.feasible_space.step
        if p.feasible_space.scale:
            fs["scale"] = p.feasible_space.scale
        if p.feasible_space.list:
            fs["list"] = list(p.feasible_space.list)
        specs.append({"name": p.name,
                      "parameterType": p.parameter_type or "double",
                      "feasibleSpace": fs})
    return SearchSpace.parse(specs)


def _cast_param(param, s: str) -> Any:
    """Wire string -> the parameter's value domain. Categorical/discrete
    values must round-trip to the SPACE's choice objects (a numeric-looking
    categorical string like "1" must stay the space's choice, not int 1)."""
    if param.type == "double":
        return float(s)
    if param.type == "int":
        return int(float(s))
    for c in param.values:
        if str(c) == s:
            return c
    return s


def _history_from_pb(space: SearchSpace, exp: "pb.ExperimentSpec",
                     trials) -> list[TrialResult]:
    # algorithms minimize; negate for maximize objectives (the experiment
    # controller's convention, hpo/algorithms/base.py)
    sign = -1.0 if exp.objective_type == "maximize" else 1.0
    by_name = {p.name: p for p in space.parameters}
    out = []
    for t in trials:
        params = {}
        for a in t.parameter_assignments:
            p = by_name.get(a.name)
            params[a.name] = _cast_param(p, a.value) if p else a.value
        value = sign * t.objective_value if t.has_objective else None
        out.append(TrialResult(params=params, value=value,
                               status=t.status or "Succeeded"))
    return out


class SuggestionService:
    """gRPC server hosting the suggestion-algorithm registry.

    Stateful algorithms (CMA-ES, hyperband) are cached per experiment name
    so repeated GetSuggestions calls continue one optimization — Katib's
    per-experiment service Deployment has the same lifetime semantics.
    """

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 max_workers: int = 4):
        import grpc
        import threading

        self._grpc = grpc
        self._algorithms: dict[str, Any] = {}
        # one lock for cache AND suggest: stateful algorithms (CMA-ES,
        # hyperband) are not thread-safe, and two concurrent first calls
        # must not each construct (and half-discard) an instance
        self._algo_lock = threading.Lock()
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers))
        handlers = {
            "GetSuggestions": grpc.unary_unary_rpc_method_handler(
                self._get_suggestions,
                request_deserializer=pb.GetSuggestionsRequest.FromString,
                response_serializer=pb.GetSuggestionsReply.SerializeToString),
            "ValidateAlgorithmSettings": grpc.unary_unary_rpc_method_handler(
                self._validate,
                request_deserializer=(
                    pb.ValidateAlgorithmSettingsRequest.FromString),
                response_serializer=(
                    pb.ValidateAlgorithmSettingsReply.SerializeToString)),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, handlers),))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        self.host = host

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "SuggestionService":
        self._server.start()
        return self

    def stop(self, grace: float = 0.5) -> None:
        self._server.stop(grace).wait()

    def _algorithm(self, exp: "pb.ExperimentSpec"):
        key = exp.name or "_anonymous"
        algo = self._algorithms.get(key)
        if algo is None:
            settings = {s.name: s.value for s in exp.algorithm_settings}
            algo = make_algorithm(exp.algorithm_name or "random",
                                  _space_from_pb(exp), settings,
                                  seed=int(exp.seed))
            self._algorithms[key] = algo
        return algo

    def _get_suggestions(self, request, context):
        try:
            with self._algo_lock:
                algo = self._algorithm(request.experiment)
                history = _history_from_pb(algo.space, request.experiment,
                                           request.trials)
                n = max(1, request.current_request_number)
                assignments = algo.suggest(n, history)
        except (SpaceError, KeyError, ValueError) as e:
            context.abort(self._grpc.StatusCode.INVALID_ARGUMENT, str(e))
        reply = pb.GetSuggestionsReply()
        for a in assignments:
            s = reply.suggestions.add()
            for name, value in a.items():
                pa = s.parameter_assignments.add()
                pa.name = name
                pa.value = str(value)
        return reply

    def _validate(self, request, context):
        try:
            settings = {s.name: s.value
                        for s in request.experiment.algorithm_settings}
            make_algorithm(request.experiment.algorithm_name or "random",
                           _space_from_pb(request.experiment), settings,
                           seed=int(request.experiment.seed))
            return pb.ValidateAlgorithmSettingsReply(error="")
        except (SpaceError, KeyError, ValueError) as e:
            return pb.ValidateAlgorithmSettingsReply(error=str(e))


class SuggestionClient:
    """The suggestion-controller side of the wire (⊘ katib
    suggestion_controller.go `SyncSuggestion` gRPC client)."""

    def __init__(self, address: str, timeout: float = 30.0):
        import grpc

        self._channel = grpc.insecure_channel(address)
        self.timeout = timeout
        self._get = self._channel.unary_unary(
            f"/{SERVICE}/GetSuggestions",
            request_serializer=pb.GetSuggestionsRequest.SerializeToString,
            response_deserializer=pb.GetSuggestionsReply.FromString)
        self._validate = self._channel.unary_unary(
            f"/{SERVICE}/ValidateAlgorithmSettings",
            request_serializer=(
                pb.ValidateAlgorithmSettingsRequest.SerializeToString),
            response_deserializer=pb.ValidateAlgorithmSettingsReply.FromString)

    @staticmethod
    def _fill_experiment(e: "pb.ExperimentSpec",
                         experiment: dict[str, Any]) -> None:
        e.name = experiment.get("name", "")
        e.algorithm_name = experiment.get("algorithm", "random")
        e.objective_type = experiment.get("objectiveType", "minimize")
        e.seed = int(experiment.get("seed", 0))
        for k, v in (experiment.get("settings") or {}).items():
            s = e.algorithm_settings.add()
            s.name, s.value = k, str(v)
        for p in experiment.get("parameters", []):
            ps = e.parameters.add()
            ps.name = p["name"]
            ps.parameter_type = p.get("parameterType", "double")
            fs = p.get("feasibleSpace", {})
            for attr in ("min", "max", "step", "scale"):
                if fs.get(attr) is not None:
                    setattr(ps.feasible_space, attr, str(fs[attr]))
            for v in fs.get("list", []):
                ps.feasible_space.list.append(str(v))

    def _cast_reply(self, experiment: dict[str, Any], name: str,
                    value: str) -> Any:
        for p in experiment.get("parameters", []):
            if p["name"] != name:
                continue
            ptype = p.get("parameterType", "double")
            if ptype == "double":
                return float(value)
            if ptype == "int":
                return int(float(value))
            # categorical/discrete: return the caller's original choice
            # object whose string form matches the wire value; discrete
            # values are floats server-side ("128" arrives as "128.0"),
            # so fall back to numeric equality
            choices = p.get("feasibleSpace", {}).get("list", [])
            for c in choices:
                if str(c) == value:
                    return c
            try:
                fv = float(value)
            except ValueError:
                return value
            for c in choices:
                try:
                    if float(c) == fv:
                        return c
                except (TypeError, ValueError):
                    continue
        return value

    def get_suggestions(self, experiment: dict[str, Any],
                        trials: list[dict[str, Any]],
                        count: int) -> list[dict[str, Any]]:
        """experiment: {name, algorithm, settings, parameters(Katib-shaped),
        objectiveType, seed}; trials: [{params, value|None, status}]."""
        req = pb.GetSuggestionsRequest(current_request_number=count)
        self._fill_experiment(req.experiment, experiment)
        for t in trials:
            pt = req.trials.add()
            pt.name = t.get("name", "")
            pt.status = t.get("status", "Succeeded")
            if t.get("value") is not None:
                pt.objective_value = float(t["value"])
                pt.has_objective = True
            for k, v in t.get("params", {}).items():
                a = pt.parameter_assignments.add()
                a.name, a.value = k, str(v)
        reply = self._get(req, timeout=self.timeout)
        return [{a.name: self._cast_reply(experiment, a.name, a.value)
                 for a in s.parameter_assignments}
                for s in reply.suggestions]

    def validate(self, experiment: dict[str, Any]) -> str:
        req = pb.ValidateAlgorithmSettingsRequest()
        self._fill_experiment(req.experiment, experiment)
        return self._validate(req, timeout=self.timeout).error

    def close(self) -> None:
        self._channel.close()
