"""NAS search-space expansion — Katib's `nasConfig` (SURVEY.md §2.3 ⊘ katib
Experiment `spec.nasConfig` + pkg/suggestion/v1beta1/nas).

Katib's NAS experiments describe a graph (numLayers) and candidate
operations; the suggestion service samples an architecture per trial. Here
the expansion is explicit and algorithm-agnostic: `nas_parameters` turns
nasConfig into one categorical parameter per layer, so EVERY suggestion
algorithm (random, TPE, GP-bayesian, CMA-ES, ...) can drive architecture
search — and the trial is an ordinary training job running the `nas_cnn`
model with the sampled ops.

The differentiable path (DARTS supernet, models/nas_cnn.py) needs no
experiment at all: one training job learns the op mixture directly.

The reinforcement path (⊘ katib ENAS) is the `enas` suggestion algorithm
(hpo/algorithms/enas.py): a REINFORCE-trained categorical policy samples
architectures per trial; point the trial template's checkpoint dir at a
shared location and trials warm-start from the shared supernet weights.

    spec:
      nasConfig:
        numLayers: 4
        operations: [conv3, conv5, maxpool, identity]   # default: all
      trialTemplate:
        spec: <job spec with ${trialParameters.op_0} ... substitutions>
"""

from __future__ import annotations

from typing import Any

from kubeflow_tpu.hpo.space import SpaceError

# kept in sync with models/nas_cnn.py OP_NAMES (asserted by tests); NOT
# imported from there so the control plane's validate path stays jax-free
OP_NAMES: tuple[str, ...] = ("conv3", "conv5", "sep3", "maxpool", "avgpool",
                             "identity")


def validate_nas_config(nas: dict[str, Any]) -> list[str]:
    errs = []
    n = nas.get("numLayers")
    if not isinstance(n, int) or n < 1:
        errs.append("nasConfig.numLayers must be an int >= 1")
    ops = nas.get("operations", list(OP_NAMES))
    if not isinstance(ops, list) or not ops:
        errs.append("nasConfig.operations must be a non-empty list")
    else:
        for op in ops:
            if op not in OP_NAMES:
                errs.append(f"nasConfig.operations: unknown op {op!r} "
                            f"(known: {', '.join(OP_NAMES)})")
    return errs


def nas_parameters(nas: dict[str, Any]) -> list[dict[str, Any]]:
    """nasConfig -> Katib-shaped categorical parameters (op_0 .. op_{L-1}).

    Raises SpaceError on malformed configs so validation surfaces the
    problem as InvalidSpec (the same channel SearchSpace.parse uses) rather
    than crashing the reconciler."""
    n = nas.get("numLayers")
    if not isinstance(n, int) or isinstance(n, bool) or n < 1:
        raise SpaceError(f"nasConfig.numLayers must be an int >= 1, "
                         f"got {n!r}")
    ops = nas.get("operations", list(OP_NAMES))
    if not isinstance(ops, list) or not ops:
        raise SpaceError("nasConfig.operations must be a non-empty list")
    ops = [str(o) for o in ops]
    return [{"name": f"op_{i}", "parameterType": "categorical",
             "feasibleSpace": {"list": ops}}
            for i in range(n)]


def effective_parameters(spec: dict[str, Any]) -> list[dict[str, Any]]:
    """The experiment's search space: explicit `parameters`, extended by the
    nasConfig expansion when present (both may coexist — e.g. searching
    architecture AND learning rate together)."""
    params = list(spec.get("parameters", []))
    nas = spec.get("nasConfig")
    if nas:
        params.extend(nas_parameters(nas))
    return params


def architecture_from_assignment(assignment: dict[str, Any],
                                 num_layers: int) -> tuple[str, ...]:
    """Collect op_i assignments back into a NasCnnConfig.ops tuple."""
    return tuple(str(assignment[f"op_{i}"]) for i in range(num_layers))
