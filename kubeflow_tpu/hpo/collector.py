"""Trial metrics collector — Katib's metrics-collector sidecar (SURVEY.md
§2.3, ⊘ katib pkg/metricscollector/v1beta1 + webhook inject_webhook.go).

The reference injects a sidecar that scrapes stdout regexes or tfevents and
pushes observations to the db-manager. Here the trial controller attaches a
collector to each trial: a `FileTail` thread that follows the trainer's
structured JSONL metric stream *while the job runs* (so early stopping sees
intermediate metrics), plus a final text scrape of pod logs for the
reference-style `name=value` stdout protocol.
"""

from __future__ import annotations

import json
import os
import re
import threading
from typing import Sequence

from kubeflow_tpu.hpo.observations import ObservationDB

# matches "loss=0.123", "accuracy = 97.5" — the Katib stdout format
_KV_RE = re.compile(
    r"(?P<name>[A-Za-z][\w./-]*)\s*=\s*"
    r"(?P<value>[-+]?\d+(?:\.\d+)?(?:[eE][-+]?\d+)?)(?![\w.])")
_STEP_RE = re.compile(r"\[step (?P<step>\d+)\]")


def parse_jsonl_line(line: str) -> tuple[int, dict[str, float]] | None:
    """One MetricsWriter record → (step, {metric: value})."""
    line = line.strip()
    if not line or not line.startswith("{"):
        return None
    try:
        rec = json.loads(line)
    except json.JSONDecodeError:
        return None
    if "metrics" not in rec:
        return None
    out = {}
    for k, v in rec["metrics"].items():
        if isinstance(v, (int, float)):
            out[k] = float(v)
    return int(rec.get("step", 0)), out


def collect_text(db: ObservationDB, trial: str, text: str,
                 metric_names: Sequence[str]) -> int:
    """Scrape free-form log text (JSONL lines and `k=v` pairs). Returns the
    number of observations recorded."""
    wanted = set(metric_names)
    n = 0
    step = 0
    for line in text.splitlines():
        rec = parse_jsonl_line(line)
        if rec is not None:
            step, metrics = rec
            for k, v in metrics.items():
                if k in wanted:
                    db.report(trial, k, v, step)
                    n += 1
            continue
        m = _STEP_RE.search(line)
        if m:
            step = int(m.group("step"))
        for kv in _KV_RE.finditer(line):
            if kv.group("name") in wanted:
                db.report(trial, kv.group("name"),
                          float(kv.group("value")), step)
                n += 1
    return n


class FileTail:
    """Follows a JSONL metrics file, reporting new records into the DB.
    Survives the file not existing yet (trainer creates it on first write)."""

    def __init__(self, db: ObservationDB, trial: str, path: str,
                 metric_names: Sequence[str], poll: float = 0.2):
        self.db = db
        self.trial = trial
        self.path = path
        self.wanted = set(metric_names)
        self.poll = poll
        self._stop = threading.Event()
        self._pos = 0
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"collector-{self.trial}")
        self._thread.start()

    def stop(self, final_pass: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if final_pass:
            self._drain()

    def _run(self) -> None:
        while not self._stop.wait(self.poll):
            self._drain()

    def _drain(self) -> None:
        if not os.path.exists(self.path):
            return
        try:
            with open(self.path) as fh:
                fh.seek(self._pos)
                chunk = fh.read()
                self._pos = fh.tell()
        except OSError:
            return
        # only complete lines; keep a partial tail for the next drain
        if chunk and not chunk.endswith("\n"):
            cut = chunk.rfind("\n") + 1
            self._pos -= len(chunk) - cut
            chunk = chunk[:cut]
        for line in chunk.splitlines():
            rec = parse_jsonl_line(line)
            if rec is None:
                continue
            step, metrics = rec
            for k, v in metrics.items():
                if k in self.wanted:
                    self.db.report(self.trial, k, v, step)
