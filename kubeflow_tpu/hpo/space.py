"""Hyperparameter search space — Katib's `parameters:` block (SURVEY.md §2.3,
⊘ katib pkg/apis/controller/experiments/v1beta1 `ParameterSpec`/`FeasibleSpace`).

Four parameter types with the Katib YAML shape:

    parameters:
      - name: lr
        parameterType: double          # double | int | categorical | discrete
        feasibleSpace: {min: 1e-4, max: 1e-1, scale: log}   # step optional
      - name: optimizer
        parameterType: categorical
        feasibleSpace: {list: [adamw, sgd, lion]}

Beyond the Katib shape we add a *unit-cube embedding* (`to_unit`/`from_unit`):
every parameter maps to [0,1], log-scaled where requested, categoricals by
index. Model-based algorithms (GP, TPE, CMA-ES) operate on the cube and decode
back — that keeps each algorithm free of per-type branching.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Sequence

import numpy as np


class SpaceError(ValueError):
    pass


@dataclasses.dataclass(frozen=True)
class Parameter:
    name: str
    type: str                       # double | int | categorical | discrete
    min: float | None = None
    max: float | None = None
    step: float | None = None
    values: tuple[Any, ...] = ()    # categorical/discrete choices
    scale: str = "linear"           # linear | log

    def __post_init__(self):
        if self.type in ("double", "int"):
            if self.min is None or self.max is None:
                raise SpaceError(f"{self.name}: min/max required for {self.type}")
            if self.max <= self.min:
                raise SpaceError(f"{self.name}: max must be > min")
            if self.scale == "log" and self.min <= 0:
                raise SpaceError(f"{self.name}: log scale requires min > 0")
        elif self.type in ("categorical", "discrete"):
            if not self.values:
                raise SpaceError(f"{self.name}: list required for {self.type}")
        else:
            raise SpaceError(f"{self.name}: unknown parameterType {self.type!r}")
        if self.scale not in ("linear", "log"):
            raise SpaceError(f"{self.name}: unknown scale {self.scale!r}")

    # -- unit-cube embedding --------------------------------------------------

    @property
    def n_choices(self) -> int:
        """Number of discrete choices (0 → continuous)."""
        if self.type in ("categorical", "discrete"):
            return len(self.values)
        if self.type == "int" and self.step in (None, 1):
            return int(self.max - self.min) + 1
        if self.step:
            return int((self.max - self.min) / self.step) + 1
        return 0

    def _lo_hi(self) -> tuple[float, float]:
        if self.scale == "log":
            return math.log(self.min), math.log(self.max)
        return float(self.min), float(self.max)

    def from_unit(self, u: float) -> Any:
        u = min(max(float(u), 0.0), 1.0)
        if self.type in ("categorical", "discrete"):
            idx = min(int(u * len(self.values)), len(self.values) - 1)
            return self.values[idx]
        lo, hi = self._lo_hi()
        x = lo + u * (hi - lo)
        if self.scale == "log":
            x = math.exp(x)
        if self.step:
            x = self.min + round((x - self.min) / self.step) * self.step
        x = min(max(x, self.min), self.max)
        return int(round(x)) if self.type == "int" else float(x)

    def to_unit(self, value: Any) -> float:
        if self.type in ("categorical", "discrete"):
            try:
                idx = self.values.index(value)
            except ValueError:
                raise SpaceError(f"{self.name}: {value!r} not in choices")
            return (idx + 0.5) / len(self.values)
        lo, hi = self._lo_hi()
        x = math.log(float(value)) if self.scale == "log" else float(value)
        return min(max((x - lo) / (hi - lo), 0.0), 1.0)

    def sample(self, rng: np.random.Generator) -> Any:
        return self.from_unit(rng.uniform())

    def grid(self, n: int) -> list[Any]:
        """Up to n distinct values spanning the space (grid search)."""
        if self.type in ("categorical", "discrete"):
            return list(self.values)
        k = self.n_choices
        if 0 < k <= n:
            n = k
        if n == 1:
            return [self.from_unit(0.5)]
        out: list[Any] = []
        for i in range(n):
            v = self.from_unit(i / (n - 1))
            if not out or v != out[-1]:
                out.append(v)
        return out


@dataclasses.dataclass(frozen=True)
class SearchSpace:
    parameters: tuple[Parameter, ...]

    @classmethod
    def parse(cls, specs: Sequence[dict[str, Any]]) -> "SearchSpace":
        """From the Katib-shaped `parameters:` list."""
        params = []
        seen: set[str] = set()
        for p in specs:
            name = p.get("name")
            if not name:
                raise SpaceError("parameter missing name")
            if name in seen:
                raise SpaceError(f"duplicate parameter {name!r}")
            seen.add(name)
            fs = p.get("feasibleSpace", {})
            ptype = p.get("parameterType", "double")
            values = fs.get("list", ())
            if ptype == "discrete":
                values = tuple(
                    float(v) if isinstance(v, str) else v for v in values)
            params.append(Parameter(
                name=name, type=ptype,
                min=None if fs.get("min") is None else float(fs["min"]),
                max=None if fs.get("max") is None else float(fs["max"]),
                step=None if fs.get("step") in (None, "") else float(fs["step"]),
                values=tuple(values),
                scale=fs.get("scale", "linear")))
        if not params:
            raise SpaceError("search space is empty")
        return cls(parameters=tuple(params))

    def __len__(self) -> int:
        return len(self.parameters)

    def __iter__(self):
        return iter(self.parameters)

    def names(self) -> list[str]:
        return [p.name for p in self.parameters]

    def sample(self, rng: np.random.Generator) -> dict[str, Any]:
        return {p.name: p.sample(rng) for p in self.parameters}

    def to_unit(self, assignment: dict[str, Any]) -> np.ndarray:
        return np.array([p.to_unit(assignment[p.name])
                         for p in self.parameters])

    def from_unit(self, u: np.ndarray) -> dict[str, Any]:
        return {p.name: p.from_unit(u[i])
                for i, p in enumerate(self.parameters)}

    def cardinality(self) -> float:
        """Total distinct points (inf if any axis is continuous)."""
        total = 1.0
        for p in self.parameters:
            k = p.n_choices
            if k == 0:
                return math.inf
            total *= k
        return total
