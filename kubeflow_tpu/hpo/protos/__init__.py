"""protoc-generated Katib suggestion-service messages (suggestion.proto).

Regenerate: scripts/gen_protos.sh.
"""
