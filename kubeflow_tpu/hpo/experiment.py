"""Experiment + Suggestion controllers — Katib's experiment/suggestion
reconcilers (SURVEY.md §2.3, §3.3, ⊘ katib
pkg/controller.v1beta1/experiment/experiment_controller.go and
pkg/controller.v1beta1/suggestion/suggestion_controller.go).

Flow (mirrors §3.3): Experiment creates one Suggestion; the experiment loop
raises `suggestion.spec.requests` as budget allows; the suggestion controller
runs the algorithm (the per-experiment "service") and appends parameter
assignments to `suggestion.status.assignments`; the experiment turns each
fresh assignment into a Trial; trial observations flow back as algorithm
history. Budget semantics are Katib's: `parallelTrialCount`, `maxTrialCount`,
`maxFailedTrialCount`, optional objective `goal`.

Experiment spec:
    kind: Experiment
    spec:
      objective:
        type: minimize | maximize
        objectiveMetricName: loss
        goal: 0.01                       # optional
        additionalMetricNames: [acc]
      algorithm: {algorithmName: tpe, algorithmSettings: {...}}
      parameters: [{name, parameterType, feasibleSpace}, ...]
      parallelTrialCount: 3
      maxTrialCount: 12
      maxFailedTrialCount: 3
      earlyStopping: {algorithmName: medianstop, algorithmSettings: {...}}
      trialTemplate:
        trialParameters: [{name: lr, reference: lr}, ...]   # optional mapping
        spec: <JAXJob spec with ${trialParameters.*}>
"""

from __future__ import annotations

import time
from typing import Any

from kubeflow_tpu.control.conditions import (JobConditionType, has_condition,
                                             is_finished, set_condition)
from kubeflow_tpu.control.controller import Controller
from kubeflow_tpu.control.frameworks import ALL_JOB_KINDS
from kubeflow_tpu.control.jobs import JOB_KIND
from kubeflow_tpu.control.store import AlreadyExistsError, new_resource
from kubeflow_tpu.hpo import algorithms as alg
from kubeflow_tpu.hpo import nas as _nas
from kubeflow_tpu.hpo.observations import ObservationDB, default_db
from kubeflow_tpu.hpo.space import SearchSpace, SpaceError
from kubeflow_tpu.hpo.trial import (EXPERIMENT_LABEL, TRIAL_KIND,
                                    trial_finished)

EXPERIMENT_KIND = "Experiment"
SUGGESTION_KIND = "Suggestion"


def max_trial_count(spec: dict[str, Any]) -> int:
    """One default for the trial budget: the finish check and the
    resumePolicy check must never disagree on it."""
    return spec.get("maxTrialCount", 12)


def validate_experiment(exp: dict[str, Any],
                        extra_job_kinds: tuple[str, ...] = ()) -> list[str]:
    """`extra_job_kinds` lets a cluster-aware caller accept custom job
    controllers registered beyond the built-in ALL_JOB_KINDS (the static
    admission layer passes nothing and rejects unknown kinds)."""
    errs = []
    spec = exp.get("spec", {})
    obj = spec.get("objective", {})
    if obj.get("type", "minimize") not in ("minimize", "maximize"):
        errs.append(f"objective.type invalid: {obj.get('type')}")
    if not obj.get("objectiveMetricName"):
        errs.append("objective.objectiveMetricName is required")
    name = spec.get("algorithm", {}).get("algorithmName", "random")
    if name not in alg.algorithm_names():
        errs.append(f"unknown algorithm {name!r}")
    nas = spec.get("nasConfig")
    if nas is not None:
        errs.extend(_nas.validate_nas_config(nas))
    try:
        SearchSpace.parse(_nas.effective_parameters(spec))
    except SpaceError as e:
        errs.append(f"parameters: {e}")
    if spec.get("resumePolicy", "Never") not in ("Never", "LongRunning",
                                                 "FromVolume"):
        errs.append(f"resumePolicy invalid: {spec.get('resumePolicy')!r} "
                    "(Never | LongRunning | FromVolume)")
    mc = spec.get("metricsCollector")
    if mc is not None and mc.get("kind", "File") not in (
            "File", "StdOut", "TensorFlowEvent"):
        errs.append(f"metricsCollector.kind invalid: {mc.get('kind')!r} "
                    "(File | StdOut | TensorFlowEvent)")
    tt = spec.get("trialTemplate", {})
    if "spec" not in tt:
        errs.append("trialTemplate.spec is required")
    known_kinds = ALL_JOB_KINDS + tuple(extra_job_kinds)
    if tt.get("kind", JOB_KIND) not in known_kinds:
        errs.append(f"trialTemplate.kind {tt.get('kind')!r} unknown "
                    f"(known: {', '.join(known_kinds)})")
    for key in ("parallelTrialCount", "maxTrialCount", "maxFailedTrialCount"):
        v = spec.get(key)
        if v is not None and (not isinstance(v, int) or v < 1):
            errs.append(f"{key} must be a positive int")
    return errs


class SuggestionController(Controller):
    """Runs the algorithm service per experiment. History is rebuilt from
    trial statuses each call, so a restarted controller resumes cleanly
    (resumePolicy analog)."""

    kind = SUGGESTION_KIND
    resync_period = 0.5

    def __init__(self, cluster, db: ObservationDB | None = None):
        super().__init__(cluster)
        self.db = db or default_db()
        self._algos: dict[str, alg.Algorithm] = {}

    def _algorithm(self, sug: dict[str, Any]) -> alg.Algorithm:
        uid = sug["metadata"]["uid"]
        if uid not in self._algos:
            spec = sug["spec"]
            self._algos[uid] = alg.make_algorithm(
                spec.get("algorithmName", "random"),
                SearchSpace.parse(spec["parameters"]),
                spec.get("algorithmSettings"),
                seed=int(sug["metadata"]["uid"][:8], 16))
        return self._algos[uid]

    def _history(self, sug: dict[str, Any]) -> list[alg.TrialResult]:
        ns = sug["metadata"].get("namespace", "default")
        maximize = sug["spec"].get("objectiveType") == "maximize"
        history = []
        for t in self.store.list(TRIAL_KIND, ns, labels={
                EXPERIMENT_LABEL: sug["spec"].get("experiment", "")}):
            if not trial_finished(t["status"]):
                continue
            value = t["status"].get("objectiveValue")
            if value is not None and maximize:
                value = -value
            status = ("Succeeded" if has_condition(
                t["status"], JobConditionType.SUCCEEDED) else
                "EarlyStopped" if has_condition(t["status"], "EarlyStopped")
                else "Failed")
            history.append(alg.TrialResult(
                params=t["spec"].get("parameterAssignments", {}),
                value=value, status=status))
        return history

    def reconcile(self, sug: dict[str, Any]) -> float | None:
        requests = sug["spec"].get("requests", 0)
        assignments = sug["status"].get("assignments", [])
        need = requests - len(assignments)
        if need <= 0:
            return None
        algorithm = self._algorithm(sug)
        algorithm.issued = len(assignments)
        batch = algorithm.suggest(need, self._history(sug))
        if not batch:
            if not algorithm.exhaustible:
                # generation-gated (PBT): the next batch unlocks when the
                # in-flight generation finishes; poll, don't complete
                return 0.5
            # algorithm exhausted (e.g. full grid enumerated)
            self.store.mutate(
                SUGGESTION_KIND, sug["metadata"]["name"],
                lambda o: o["status"].update(exhausted=True),
                sug["metadata"].get("namespace", "default"))
            return None
        self.store.mutate(
            SUGGESTION_KIND, sug["metadata"]["name"],
            lambda o: o["status"].setdefault("assignments", []).extend(batch),
            sug["metadata"].get("namespace", "default"))
        return 0.0


class ExperimentController(Controller):
    kind = EXPERIMENT_KIND
    owned_kinds = (SUGGESTION_KIND, TRIAL_KIND)
    resync_period = 0.5

    def _should_resume(self, exp: dict[str, Any]) -> bool:
        """Resumable (⊘ katib resumePolicy) when the budget that finished
        the experiment has since been raised. Goal-reached and failed
        experiments stay final."""
        if exp["spec"].get("resumePolicy", "Never") not in (
                "LongRunning", "FromVolume"):
            return False
        # cheap precheck from status (maintained by reconcile, final at
        # finish time): finished LongRunning experiments resync forever,
        # and must not scan the store every 0.5s in steady state
        created = exp["status"].get("trials", {}).get("created", 0)
        if created >= max_trial_count(exp["spec"]):
            return False
        conds = exp["status"].get("conditions", ())
        done = next((c for c in conds
                     if c["type"] == JobConditionType.SUCCEEDED
                     and c["status"] == "True"), None)
        if done is None or done.get("reason") != "MaxTrialsReached":
            return False
        ns = exp["metadata"].get("namespace", "default")
        sug = self.store.try_get(SUGGESTION_KIND,
                                 exp["metadata"]["name"], ns)
        if sug and sug["status"].get("exhausted"):
            return False   # nothing left to suggest (e.g. full grid):
                           # reopening would immediately re-finish, forever
        return True

    def reconcile(self, exp: dict[str, Any]) -> float | None:
        name = exp["metadata"]["name"]
        ns = exp["metadata"].get("namespace", "default")
        status = exp["status"]
        if is_finished(status):
            if self._should_resume(exp):
                # ⊘ katib resumePolicy LongRunning/FromVolume: raising
                # maxTrialCount on a MaxTrialsReached experiment reopens
                # it; the algorithm rebuilds from trial history
                self.store.mutate(EXPERIMENT_KIND, name, lambda o: (
                    o["status"].__setitem__("conditions", [
                        c for c in o["status"].get("conditions", ())
                        if c["type"] != JobConditionType.SUCCEEDED]),
                    o["status"].pop("completionTime", None),
                    set_condition(o["status"], JobConditionType.RESTARTING,
                                  "ExperimentResumed",
                                  "maxTrialCount raised; resuming")), ns)
                return 0.0
            return None

        from kubeflow_tpu.control.jobs import JAXJobController

        custom = tuple(c.kind for c in self.cluster.controllers
                       if isinstance(c, JAXJobController)
                       and c.kind not in ALL_JOB_KINDS)
        errs = validate_experiment(exp, extra_job_kinds=custom)
        if errs:
            self._finish(exp, JobConditionType.FAILED, "InvalidSpec",
                         "; ".join(errs))
            return None
        if not status.get("conditions"):
            self.store.mutate(EXPERIMENT_KIND, name, lambda o: (
                o["status"].update(startTime=time.time()),
                set_condition(o["status"], JobConditionType.CREATED,
                              "ExperimentCreated", "experiment created")), ns)
            return 0.0

        spec = exp["spec"]
        trials = self.store.list(TRIAL_KIND, ns,
                                 labels={EXPERIMENT_LABEL: name})
        running = [t for t in trials if not trial_finished(t["status"])]
        succeeded = [t for t in trials if has_condition(
            t["status"], JobConditionType.SUCCEEDED)]
        early = [t for t in trials if has_condition(t["status"],
                                                    "EarlyStopped")]
        failed = [t for t in trials if has_condition(
            t["status"], JobConditionType.FAILED)]

        optimal = self._optimal(spec, succeeded + early)
        counts = {"running": len(running), "succeeded": len(succeeded),
                  "earlyStopped": len(early), "failed": len(failed),
                  "created": len(trials)}

        def write(o):
            o["status"]["trials"] = counts
            if optimal is not None:
                o["status"]["currentOptimalTrial"] = optimal
            if running:
                set_condition(o["status"], JobConditionType.RUNNING,
                              "ExperimentRunning", "trials running")
        self.store.mutate(EXPERIMENT_KIND, name, write, ns)

        # Katib semantics: fail once failed trials REACH the budget;
        # maxFailedTrialCount=0 means "fail on the first failure", not
        # "fail immediately with none".
        max_failed = spec.get("maxFailedTrialCount", 3)
        if failed and len(failed) >= max(1, max_failed):
            self._finish(exp, JobConditionType.FAILED,
                         "MaxFailedTrialsReached",
                         f"{len(failed)} failed trials >= {max_failed}")
            return None
        if self._goal_reached(spec, optimal):
            self._finish(exp, JobConditionType.SUCCEEDED, "GoalReached",
                         f"objective goal reached: {optimal['observation']}")
            return None
        max_trials = max_trial_count(spec)
        done = len(succeeded) + len(early) + len(failed)
        sug = self.store.try_get(SUGGESTION_KIND, name, ns)
        exhausted = bool(sug and sug["status"].get("exhausted"))
        if (done >= max_trials or (exhausted and not running
                                   and self._consumed(sug) >= len(trials))):
            self._finish(exp, JobConditionType.SUCCEEDED, "MaxTrialsReached",
                         f"{done} trials completed")
            return None

        # -- budget: request + materialize suggestions ------------------------
        parallel = spec.get("parallelTrialCount", 3)
        want_new = min(parallel - len(running), max_trials - len(trials))
        if want_new > 0:
            sug = self._ensure_suggestion(exp)
            target = len(trials) + want_new
            if sug["spec"].get("requests", 0) < target:
                self.store.mutate(
                    SUGGESTION_KIND, name,
                    lambda o: o["spec"].update(requests=target), ns)
            for idx, assignment in enumerate(
                    sug["status"].get("assignments", [])):
                self._ensure_trial(exp, idx, assignment)
        return 0.2

    # -- helpers --------------------------------------------------------------

    @staticmethod
    def _consumed(sug) -> int:
        return len(sug["status"].get("assignments", [])) if sug else 0

    def _ensure_suggestion(self, exp: dict[str, Any]) -> dict[str, Any]:
        name = exp["metadata"]["name"]
        ns = exp["metadata"].get("namespace", "default")
        sug = self.store.try_get(SUGGESTION_KIND, name, ns)
        if sug is not None:
            return sug
        spec = exp["spec"]
        sug = new_resource(SUGGESTION_KIND, name, spec={
            "experiment": name,
            "algorithmName": spec.get("algorithm", {}).get("algorithmName",
                                                           "random"),
            "algorithmSettings": spec.get("algorithm", {}).get(
                "algorithmSettings", {}),
            "parameters": _nas.effective_parameters(spec),
            "objectiveType": spec.get("objective", {}).get("type",
                                                           "minimize"),
            "requests": 0,
        }, namespace=ns, owner=exp)
        try:
            return self.store.create(sug)
        except AlreadyExistsError:
            return self.store.get(SUGGESTION_KIND, name, ns)

    def _trial_spec(self, exp: dict[str, Any],
                    assignment: dict[str, Any]) -> dict[str, Any]:
        spec = exp["spec"]
        tt = spec.get("trialTemplate", {})
        # trialParameters may rename: template placeholder name → space name.
        # parameterAssignments stays space-keyed (it is the algorithm-history
        # record); the renamed map only drives template substitution.
        mapping = {p.get("name"): p.get("reference", p.get("name"))
                   for p in tt.get("trialParameters", [])}
        substitutions = ({tp_name: assignment[ref]
                          for tp_name, ref in mapping.items()}
                         if mapping else dict(assignment))
        return {
            "experiment": exp["metadata"]["name"],
            "parameterAssignments": dict(assignment),
            "substitutions": substitutions,
            "objective": spec.get("objective", {}),
            "template": tt["spec"],
            "templateKind": tt.get("kind", JOB_KIND),
            "earlyStopping": spec.get("earlyStopping"),
            # ⊘ katib Experiment.spec.metricsCollectorSpec: collector kind +
            # source, propagated to every trial
            "metricsCollector": spec.get("metricsCollector"),
        }

    def _ensure_trial(self, exp: dict[str, Any], idx: int,
                      assignment: dict[str, Any]) -> None:
        name = f"{exp['metadata']['name']}-{idx:04d}"
        ns = exp["metadata"].get("namespace", "default")
        if self.store.try_get(TRIAL_KIND, name, ns) is not None:
            return
        trial = new_resource(
            TRIAL_KIND, name, spec=self._trial_spec(exp, assignment),
            namespace=ns,
            labels={EXPERIMENT_LABEL: exp["metadata"]["name"]},
            owner=exp)
        try:
            self.store.create(trial)
        except AlreadyExistsError:
            pass

    def _optimal(self, spec: dict[str, Any],
                 finished: list[dict[str, Any]]) -> dict[str, Any] | None:
        maximize = spec.get("objective", {}).get("type") == "maximize"
        best, best_v = None, None
        for t in finished:
            v = t["status"].get("objectiveValue")
            if v is None:
                continue
            if best_v is None or (v > best_v if maximize else v < best_v):
                best, best_v = t, v
        if best is None:
            return None
        return {
            "bestTrialName": best["metadata"]["name"],
            "parameterAssignments": best["spec"].get("parameterAssignments",
                                                     {}),
            "observation": best["status"].get("observation"),
            "objectiveValue": best_v,
        }

    def _goal_reached(self, spec: dict[str, Any],
                      optimal: dict[str, Any] | None) -> bool:
        goal = spec.get("objective", {}).get("goal")
        if goal is None or optimal is None:
            return False
        v = optimal["objectiveValue"]
        if spec.get("objective", {}).get("type") == "maximize":
            return v >= goal
        return v <= goal

    def _finish(self, exp: dict[str, Any], ctype: str, reason: str,
                message: str) -> None:
        ns = exp["metadata"].get("namespace", "default")
        self.store.mutate(EXPERIMENT_KIND, exp["metadata"]["name"],
                          lambda o: (
                              o["status"].update(completionTime=time.time()),
                              set_condition(o["status"], ctype, reason,
                                            message)), ns)
