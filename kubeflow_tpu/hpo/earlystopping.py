"""Early-stopping rules — Katib's medianstop service (SURVEY.md §2.3,
⊘ katib pkg/earlystopping/v1beta1/medianstop/service.py).

Median-stopping rule (Golovin et al., Vizier): stop a running trial at step s
if its best objective so far is worse than the median of the *running
averages up to step s* of all completed trials. Settings (Katib names):
`min_trials_required` (default 3), `start_step` (default 4).
"""

from __future__ import annotations

import statistics
from typing import Sequence

from kubeflow_tpu.hpo.observations import ObservationDB


class MedianStop:
    name = "medianstop"

    def __init__(self, settings: dict | None = None):
        s = settings or {}
        self.min_trials = int(s.get("min_trials_required", 3))
        self.start_step = int(s.get("start_step", 4))

    def should_stop(self, db: ObservationDB, trial: str, metric: str,
                    maximize: bool, completed: Sequence[str]) -> bool:
        if len(completed) < self.min_trials:
            return False
        obs = db.get(trial, metric)
        if not obs:
            return False
        step = obs[-1].step
        if step < self.start_step:
            return False
        best = (max if maximize else min)(o.value for o in obs)
        avgs = []
        for other in completed:
            vals = [o.value for o in db.get(other, metric) if o.step <= step]
            if vals:
                avgs.append(sum(vals) / len(vals))
        if len(avgs) < self.min_trials:
            return False
        med = statistics.median(avgs)
        return best < med if maximize else best > med


def make_early_stopping(name: str, settings: dict | None = None):
    if name in ("medianstop", "median"):
        return MedianStop(settings)
    raise ValueError(f"unknown early-stopping algorithm {name!r}")
