"""Trial controller — Katib's trial reconciler (SURVEY.md §2.3, §3.3,
⊘ katib pkg/controller.v1beta1/trial/trial_controller.go).

A Trial materializes one point of the search space: it instantiates the
experiment's trialTemplate (a JAXJob spec with `${trialParameters.*}`
placeholders substituted), attaches a metrics collector to the running job,
extracts the objective observation on completion, and applies early stopping
against sibling trials.

Spec:
    kind: Trial
    spec:
      experiment: my-exp
      parameterAssignments: {lr: 0.01, layers: 4}
      objective: {type: minimize, objectiveMetricName: loss,
                  additionalMetricNames: [...], metricStrategies: {loss: min}}
      template: <JAXJob spec>          # placeholders already wired by the
      earlyStopping: {...}             # experiment controller
Status: conditions (Created → Running → Succeeded | Failed | EarlyStopped)
plus `observation: {metrics: [{name, latest, min, max}]}`.
"""

from __future__ import annotations

import copy
import os
import re
import threading
from typing import Any

from kubeflow_tpu.control.conditions import (JobConditionType, has_condition,
                                             is_finished, set_condition)
from kubeflow_tpu.control.controller import Controller
from kubeflow_tpu.control.frameworks import ALL_JOB_KINDS
from kubeflow_tpu.control.jobs import JOB_KIND, JOB_NAME_LABEL
from kubeflow_tpu.control.store import AlreadyExistsError, new_resource
from kubeflow_tpu.hpo.collector import FileTail, collect_text
from kubeflow_tpu.hpo.earlystopping import make_early_stopping
from kubeflow_tpu.hpo.observations import ObservationDB, default_db

TRIAL_KIND = "Trial"
EXPERIMENT_LABEL = "kubeflow-tpu/experiment"
EARLY_STOPPED = "EarlyStopped"

_PLACEHOLDER = re.compile(r"\$\{trialParameters\.([\w.-]+)\}")


def trial_finished(status: dict[str, Any]) -> bool:
    return is_finished(status) or has_condition(status, EARLY_STOPPED)


def substitute(node: Any, assignments: dict[str, Any]) -> Any:
    """Replace ${trialParameters.x} through a spec tree. A string that is
    exactly one placeholder becomes the typed value; mixed strings
    interpolate."""
    if isinstance(node, dict):
        return {k: substitute(v, assignments) for k, v in node.items()}
    if isinstance(node, list):
        return [substitute(v, assignments) for v in node]
    if isinstance(node, str):
        m = _PLACEHOLDER.fullmatch(node)
        if m:
            if m.group(1) not in assignments:
                raise KeyError(f"unresolved trial parameter {m.group(1)!r}")
            return assignments[m.group(1)]
        return _PLACEHOLDER.sub(
            lambda mm: str(assignments[mm.group(1)]), node)
    return node


class TrialController(Controller):
    kind = TRIAL_KIND
    # a trialTemplate may instantiate ANY training job kind (the reference's
    # trials launch batch Jobs / TFJobs / PyTorchJobs, SURVEY.md §2.3)
    owned_kinds = ALL_JOB_KINDS
    resync_period = 0.5   # early stopping needs a frequent look

    def __init__(self, cluster, db: ObservationDB | None = None,
                 metrics_dir: str | None = None):
        super().__init__(cluster)
        self.db = db or default_db()
        self.metrics_dir = metrics_dir or os.path.join(
            os.environ.get("TMPDIR", "/tmp"), "kubeflow-tpu-metrics")
        os.makedirs(self.metrics_dir, exist_ok=True)
        self._collectors: dict[str, FileTail] = {}
        self._clock = threading.Lock()

    # -- reconcile -----------------------------------------------------------

    def reconcile(self, trial: dict[str, Any]) -> float | None:
        name = trial["metadata"]["name"]
        ns = trial["metadata"].get("namespace", "default")
        status = trial["status"]
        if trial_finished(status):
            self._stop_collector(trial, final=False)
            return None

        if not status.get("conditions"):
            self.store.mutate(TRIAL_KIND, name, lambda o: set_condition(
                o["status"], JobConditionType.CREATED, "TrialCreated",
                f"Trial {name} created."), ns)
            return 0.0

        job_kind = self._job_kind(trial)
        job = self.store.try_get(job_kind, name, ns)
        if job is None:
            # the kind must be reconciled by a TRAINING-JOB controller
            # (JAXJobController engine or a subclass): a job nobody
            # reconciles — or a non-job kind like 'Trial' itself — would
            # hang the trial (and the experiment) forever
            from kubeflow_tpu.control.jobs import JAXJobController

            job_controllers = {c.kind for c in self.cluster.controllers
                               if isinstance(c, JAXJobController)}
            if job_kind not in job_controllers:
                self.store.mutate(TRIAL_KIND, name, lambda o: set_condition(
                    o["status"], JobConditionType.FAILED, "NoController",
                    f"no training-job controller registered for "
                    f"trialTemplate kind {job_kind!r}"), ns)
                return None
            self._create_job(trial)
            return 0.1

        if has_condition(job["status"], JobConditionType.SUCCEEDED):
            self._complete(trial, job, JobConditionType.SUCCEEDED)
            return None
        if has_condition(job["status"], JobConditionType.FAILED):
            self._complete(trial, job, JobConditionType.FAILED)
            return None

        if has_condition(job["status"], JobConditionType.RUNNING):
            if not has_condition(status, JobConditionType.RUNNING):
                self.store.mutate(TRIAL_KIND, name, lambda o: set_condition(
                    o["status"], JobConditionType.RUNNING, "JobRunning",
                    "trial job is running"), ns)
            self._ensure_collector(trial)
            if self._maybe_early_stop(trial):
                return None
        return 0.2

    # -- job materialization --------------------------------------------------

    def _metrics_path(self, trial: dict[str, Any]) -> str:
        return os.path.join(self.metrics_dir,
                            f"{trial['metadata']['uid']}.jsonl")

    def _metric_names(self, trial: dict[str, Any]) -> list[str]:
        obj = trial["spec"].get("objective", {})
        names = [obj.get("objectiveMetricName", "loss")]
        names += list(obj.get("additionalMetricNames", ()))
        return names

    @staticmethod
    def _job_kind(trial: dict[str, Any]) -> str:
        return trial["spec"].get("templateKind", JOB_KIND)

    def _create_job(self, trial: dict[str, Any]) -> None:
        ns = trial["metadata"].get("namespace", "default")
        name = trial["metadata"]["name"]
        assignments = trial["spec"].get(
            "substitutions", trial["spec"].get("parameterAssignments", {}))
        spec = substitute(copy.deepcopy(trial["spec"]["template"]), assignments)
        # inject trial identity + metrics stream target into every replica
        mc = trial["spec"].get("metricsCollector") or {}
        for rspec in spec.get("replicaSpecs", {}).values():
            env = rspec.setdefault("template", {}).setdefault("env", {})
            env.setdefault("KTPU_TRIAL_NAME", name)
            env.setdefault("KTPU_METRICS_FILE", self._metrics_path(trial))
            if mc.get("kind") == "TensorFlowEvent":
                env.setdefault("KTPU_TFEVENTS_DIR",
                               self._tfevents_dir(trial, mc))
        job = new_resource(
            self._job_kind(trial), name, spec=spec, namespace=ns,
            labels={EXPERIMENT_LABEL:
                    trial["spec"].get("experiment", ""),
                    "kubeflow-tpu/trial": name},
            owner=trial)
        try:
            self.store.create(job)
        except AlreadyExistsError:
            pass

    # -- metrics & completion -------------------------------------------------

    def _ensure_collector(self, trial: dict[str, Any]) -> None:
        uid = trial["metadata"]["uid"]
        with self._clock:
            if uid in self._collectors:
                return
            mc = trial["spec"].get("metricsCollector") or {}
            if mc.get("kind") == "TensorFlowEvent":
                # ⊘ katib tfevent-metricscollector: follow the trial's
                # tensorboard logdir instead of the JSONL stream
                from kubeflow_tpu.hpo.tfevents import TfEventsTail

                tail = TfEventsTail(
                    self.db, trial["metadata"]["name"],
                    self._tfevents_dir(trial, mc),
                    self._metric_names(trial))
            else:
                tail = FileTail(self.db, trial["metadata"]["name"],
                                self._metrics_path(trial),
                                self._metric_names(trial))
            self._collectors[uid] = tail
        tail.start()

    def _tfevents_dir(self, trial: dict[str, Any],
                      mc: dict[str, Any]) -> str:
        """Source logdir for a TensorFlowEvent collector. A configured
        fileSystemPath is namespaced per trial (in Katib the path is each
        pod's own container FS; here all trials share the host FS, so a
        shared dir would cross-contaminate sibling trials' series)."""
        uid = trial["metadata"]["uid"]
        path = (mc.get("source", {}).get("fileSystemPath", {}).get("path"))
        if path:
            return os.path.join(path, uid)
        return os.path.join(self.metrics_dir, f"{uid}-tfevents")

    def _stop_collector(self, trial: dict[str, Any], final: bool) -> None:
        with self._clock:
            tail = self._collectors.pop(trial["metadata"]["uid"], None)
        if tail is not None:
            tail.stop(final_pass=final)

    def _scrape_logs(self, trial: dict[str, Any]) -> None:
        """Final stdout scrape (reference-style regex path) for jobs that
        never wrote the structured file."""
        name = trial["metadata"]["name"]
        ns = trial["metadata"].get("namespace", "default")
        executor = getattr(self.cluster, "executor", None)
        if executor is None:
            return
        for pod in self.store.list("Pod", ns, labels={JOB_NAME_LABEL: name}):
            collect_text(self.db, name, executor.logs(
                pod["metadata"]["name"], ns), self._metric_names(trial))

    def observation(self, trial: dict[str, Any]) -> dict[str, Any] | None:
        """Aggregate the DB series into Katib's observation shape."""
        name = trial["metadata"]["name"]
        metrics = []
        for mname in self._metric_names(trial):
            obs = self.db.get(name, mname)
            if not obs:
                continue
            vals = [o.value for o in obs]
            metrics.append({"name": mname, "latest": vals[-1],
                            "min": min(vals), "max": max(vals)})
        return {"metrics": metrics} if metrics else None

    def objective_value(self, trial: dict[str, Any]) -> float | None:
        """Extract the objective per metricStrategies (default: best value in
        the objective direction, Katib's default extraction)."""
        obj = trial["spec"].get("objective", {})
        mname = obj.get("objectiveMetricName", "loss")
        strategy = obj.get("metricStrategies", {}).get(
            mname, "max" if obj.get("type") == "maximize" else "min")
        obs = self.db.get(trial["metadata"]["name"], mname)
        if not obs:
            return None
        vals = [o.value for o in obs]
        if strategy == "latest":
            return vals[-1]
        return max(vals) if strategy == "max" else min(vals)

    def _complete(self, trial: dict[str, Any], job: dict[str, Any],
                  outcome: str) -> None:
        name = trial["metadata"]["name"]
        ns = trial["metadata"].get("namespace", "default")
        # ensure a collector exists so fast jobs that finished before the
        # Running edge still get their metrics file drained
        self._ensure_collector(trial)
        self._stop_collector(trial, final=True)
        self._scrape_logs(trial)
        observation = self.observation(trial)
        value = self.objective_value(trial)

        def write(o):
            if observation:
                o["status"]["observation"] = observation
            if value is not None:
                o["status"]["objectiveValue"] = value
            if outcome == JobConditionType.SUCCEEDED and value is None:
                set_condition(o["status"], JobConditionType.FAILED,
                              "MetricsUnavailable",
                              "job succeeded but objective metric missing")
            elif outcome == JobConditionType.SUCCEEDED:
                set_condition(o["status"], JobConditionType.SUCCEEDED,
                              "TrialSucceeded", "trial completed")
            else:
                set_condition(o["status"], JobConditionType.FAILED,
                              "TrialFailed", "trial job failed")
        self.store.mutate(TRIAL_KIND, name, write, ns)

    # -- early stopping -------------------------------------------------------

    def _maybe_early_stop(self, trial: dict[str, Any]) -> bool:
        es = trial["spec"].get("earlyStopping")
        if not es:
            return False
        name = trial["metadata"]["name"]
        ns = trial["metadata"].get("namespace", "default")
        obj = trial["spec"].get("objective", {})
        rule = make_early_stopping(es.get("algorithmName", "medianstop"),
                                   es.get("algorithmSettings"))
        completed = [
            t["metadata"]["name"]
            for t in self.store.list(TRIAL_KIND, ns, labels={
                EXPERIMENT_LABEL: trial["spec"].get("experiment", "")})
            if has_condition(t["status"], JobConditionType.SUCCEEDED)]
        if not rule.should_stop(
                self.db, name, obj.get("objectiveMetricName", "loss"),
                obj.get("type") == "maximize", completed):
            return False
        self._stop_collector(trial, final=True)
        observation = self.observation(trial)
        value = self.objective_value(trial)
        self.store.try_delete(self._job_kind(trial), name, ns)

        def write(o):
            if observation:
                o["status"]["observation"] = observation
            if value is not None:
                o["status"]["objectiveValue"] = value
            set_condition(o["status"], EARLY_STOPPED, "MedianStopRule",
                          "trial stopped early: below median of completed "
                          "trials")
        self.store.mutate(TRIAL_KIND, name, write, ns)
        return True
